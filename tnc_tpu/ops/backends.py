"""Execution backends for compiled contraction programs.

The reference dispatches its pairwise kernel at build time (TBLIS vs MKL
behind the ``mkl`` cargo feature, ``README.md`` Features); here the
contractor is a runtime-pluggable backend:

- :class:`NumpyBackend` — the CPU oracle, complex128.
- :class:`JaxBackend` — the TPU path: the whole program is traced once and
  ``jax.jit``-compiled with **all input buffers donated**, so XLA reuses
  HBM for intermediates and the peak matches the analytic
  ``contract_size_tensors`` prediction. Matmuls land on the MXU; default
  dtype is complex64 (TPU has no native f64; parity target is 1e-5).

Compiled executables are cached by program signature + dtype, so repeated
contractions of equal-shaped networks (e.g. amplitude sweeps) recompile
nothing.
"""

from __future__ import annotations

import logging
import os
import threading
import warnings
from collections import OrderedDict
from typing import Any, Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.ops.program import ContractionProgram
from tnc_tpu.resilience import faultinject as _faults
from tnc_tpu.resilience import retry as _retry

logger = logging.getLogger(__name__)


class Backend:
    name: str = "base"
    # True when execute_sliced accepts ckpt= / on_slice= (slice-boundary
    # checkpointing + cooperative preemption); callers (the elastic
    # serving layer) only pass those kwargs when the flag is set, so a
    # backend without them keeps serving whole runs unchanged
    supports_slice_hooks: bool = False

    def execute(self, program: ContractionProgram, arrays: Sequence[Any]) -> np.ndarray:
        raise NotImplementedError

    def execute_sliced(
        self,
        sp,
        arrays: Sequence[Any],
        max_slices: int | None = None,
        host: bool = True,
        hoist: bool | None = None,
        slice_range: tuple[int, int] | None = None,
    ):
        """``slice_range=(lo, hi)``: partial sum over that contiguous
        slice shard only (the multi-host serving shape). Part of the
        backend contract — subclasses must accept it (callers only pass
        it when actually sharding, so a legacy subclass without the
        parameter keeps working for whole-range execution)."""
        raise NotImplementedError


def _lanemix_jax(x, w, idx):
    """Static permutation of the trailing ``w``-wide lane window:
    ``out[..., j] = flat[..., idx[j]]``. Executed as an exact one-hot
    matmul on the MXU (``precision=HIGHEST`` — every output element is a
    single 1.0·x product, so the result is bit-exact) or, for wide
    windows, a gather. ``TNC_TPU_LANEMIX=take`` forces the gather."""
    import jax.numpy as jnp
    from jax import lax

    x2 = x.reshape((-1, w))
    mode, cap = lanemix_env()
    if mode == "take" or w > int(cap):
        return jnp.take(x2, jnp.asarray(idx, dtype="int32"), axis=1)
    p = np.zeros((w, w), dtype=np.float32)
    p[np.asarray(idx), np.arange(w)] = 1
    pc = jnp.asarray(p, dtype=x2.dtype)
    # per-operand precision: the data side needs the full 3-term bf16
    # split to pass through exactly; the one-hot side is exact in one
    # term (every output is a single 1.0·x product) — 3 MXU passes, not 6
    return lax.dot_general(
        x2,
        pc,
        (((1,), (0,)), ((), ())),
        precision=(lax.Precision.HIGHEST, lax.Precision.DEFAULT),
    )


def _prep_operand(xp, buf, view, perm, dot_shape, ops=None):
    """Stored buffer → ``(k, free-run dims…)`` dot operand: reshape to the
    fused view, one macro transpose to (contract…, free…), and a
    leading-axes merge of the contract runs (layout-free on TPU — tiling
    only constrains trailing dims). See :mod:`tnc_tpu.ops.program`.

    When the compiler attached a staged plan (``ops``), the device path
    executes it instead — a sequence of minor-dim-safe reshapes,
    leading-dim transposes, and lane permutations that never materializes
    a tile-padded buffer (the naive path's failure mode on high-rank
    shuffles). The host oracle keeps the naive pair (same semantics)."""
    if ops is not None and xp is not np:
        x = buf
        for op in ops:
            if op[0] == "reshape":
                x = x.reshape(op[1])
            elif op[0] == "transpose":
                x = xp.transpose(x, op[1])
            else:  # ("lanemix", W, idx)
                x = _lanemix_jax(x, op[1], op[2])
        return x.reshape(dot_shape)
    v = buf.reshape(view)
    if perm is not None:
        v = xp.transpose(v, perm)
    return v.reshape(dot_shape)


def apply_step(xp, a: Any, b: Any, step) -> Any:
    """One pairwise contraction; the single source of truth for the step
    kernel, shared by the whole-program, sliced-loop, and chunked
    executors.

    Device path: one ``lax.dot_general`` contracting the single leading
    ``k`` dim of both operands — XLA performs no internal relayout and
    every materialized buffer keeps a large minor dim (see
    :mod:`tnc_tpu.ops.program`). Host path: the equivalent 2-D matmul."""
    av = _prep_operand(xp, a, step.a_view, step.a_perm, step.a_dot, step.a_ops)
    bv = _prep_operand(xp, b, step.b_view, step.b_perm, step.b_dot, step.b_ops)
    if xp is np:
        a2 = (
            av.reshape(step.a_mat)
            if step.a_cfirst
            else av.reshape(step.a_mat[::-1]).T
        )  # (k, m)
        b2 = (
            bv.reshape(step.b_mat)
            if step.b_cfirst
            else bv.reshape(step.b_mat[::-1]).T
        )  # (k, n)
        out = (b2.T @ a2) if step.swap else (a2.T @ b2)
        return out.reshape(step.out_store)
    from jax import lax

    ca = (0,) if step.a_cfirst else (len(step.a_dot) - 1,)
    cb = (0,) if step.b_cfirst else (len(step.b_dot) - 1,)
    if step.swap:
        out = lax.dot_general(bv, av, ((cb, ca), ((), ())))
    else:
        out = lax.dot_general(av, bv, ((ca, cb), ((), ())))
    return out.reshape(step.out_store)


def _run_steps(xp, program: ContractionProgram, buffers: list[Any]) -> Any:
    """Execute all steps; returns the result in **stored** (merged) shape —
    callers reshape to ``program.result_shape`` on the host, so the jit
    output never materializes a high-rank tile-padded array."""
    for step in program.steps:
        buffers[step.lhs] = apply_step(xp, buffers[step.lhs], buffers[step.rhs], step)
        buffers[step.rhs] = None  # free eagerly
    return buffers[program.result_slot]


def dtype_width(dtype) -> float:
    """Element width in bytes of a backend dtype (name string, numpy
    dtype, or anything ``np.dtype`` accepts) — the ONE rule every
    predicted-bytes computation shares (step spans, prelude/residual
    byte counters, the calibration fit). Split-complex pairs carry the
    same bytes as the complex dtype they represent, so no special case.

    >>> dtype_width("complex64"), dtype_width(np.complex128)
    (8.0, 16.0)
    """
    try:
        return float(np.dtype(dtype).itemsize)
    except TypeError:
        return 16.0 if "128" in str(dtype) else 8.0


def run_steps_timed(
    xp,
    program: ContractionProgram,
    buffers: list[Any],
    dtype_bytes: float = 16.0,
    split_complex: bool = False,
    precision: str | None = None,
    sync=None,
    policy=None,
) -> Any:
    """Step-timed variant of :func:`_run_steps`: one obs span per
    :class:`~tnc_tpu.ops.program.PairStep`, named ``step[i] MxK·KxN``
    and carrying the step's *predicted* cost (``flops``, ``bytes_in``,
    ``bytes_out``) next to the span's *measured* wall time — the raw
    samples :mod:`tnc_tpu.obs.calibrate` fits its device model from.

    ``sync`` (JAX path: ``jax.block_until_ready``) forces each step's
    result before its span closes, so the measured time is device wall
    time, not async enqueue. The host oracle passes no ``sync`` — numpy
    is synchronous already. Same result contract as ``_run_steps``
    (stored shape). Must not be called under jit tracing (the spans
    would measure trace time once, not run time).

    Each span is tagged ``executor="numpy"|"jax"`` so the calibration
    fit never blends host- and device-measured samples of the same step
    into one "device" model, plus the step's shape ``bucket``
    (small/medium/stem), kernel ``mode``, and mode-credited
    ``flops_effective`` — the per-bucket MFU inputs.

    ``policy`` (a :class:`tnc_tpu.ops.split_complex.KernelPolicy`,
    split mode only): steps promote per the kernel ladder, and a fused
    chain emits ONE ``step[s..e]`` span carrying the whole run's
    summed predicted cost — the span count IS the dispatch count, so
    chain fusion is directly visible as fewer step spans.
    """
    from tnc_tpu.ops.program import step_elems, step_flops, step_label
    from tnc_tpu.ops.split_complex import (
        effective_step_flops,
        resolved_step_mode,
        step_bucket,
    )

    executor = "numpy" if xp is np else "jax"
    if not split_complex:
        policy = None

    if split_complex:
        from tnc_tpu.ops.split_complex import apply_step_split

        def kernel(a, b, st, mode=None, precision_mode=None):
            return apply_step_split(
                xp, a, b, st, precision, mode=mode,
                precision_mode=precision_mode,
            )

    else:

        def kernel(a, b, st, mode=None, precision_mode=None):
            return apply_step(xp, a, b, st)

    steps = program.steps
    chain_end = {s: e for s, e in policy.chains} if policy is not None else {}
    i = 0
    while i < len(steps):
        end = chain_end.get(i)
        if end is not None:
            from tnc_tpu.ops.split_complex import run_chain_split

            group = steps[i:end]
            # HBM traffic of ONE fused dispatch: the head's two
            # operands plus each link's non-carried operand in (PLUS
            # their prep passes — non-carried operands with a macro
            # transpose are materialized by prep_kl before entering
            # the kernel, the same read+write step_prep_elems prices
            # on single steps; only the CARRIED operand is
            # transpose-free by chain_groups' admission rule), the
            # final result out — carried intermediates live in VMEM
            # and never touch HBM, so summing per-step elems would
            # overstate the chain's bytes and bias the calibration fit
            import math as _math

            def _op_elems(view, perm, ops):
                prep = 2.0 if (perm is not None or ops) else 0.0
                return (1.0 + prep) * float(_math.prod(view))

            head = group[0]
            elems_in = _op_elems(
                head.a_view, head.a_perm, head.a_ops
            ) + _op_elems(head.b_view, head.b_perm, head.b_ops)
            run_slot = head.lhs
            for st in group[1:]:
                if st.lhs == run_slot:
                    elems_in += _op_elems(st.b_view, st.b_perm, st.b_ops)
                else:
                    elems_in += _op_elems(st.a_view, st.a_perm, st.a_ops)
                run_slot = st.lhs
            chain_rung = policy.precision_mode(i) if policy else ""
            with obs.span(
                f"step[{i}..{end - 1}] chain x{len(group)}",
                executor=executor,
                flops=sum(step_flops(st) for st in group),
                bytes_in=elems_in * dtype_bytes,
                bytes_out=step_elems(group[-1])[1] * dtype_bytes,
                # the calibrated chain ceiling can pull medium-bucket
                # steps into a chain — report the heaviest member's
                # bucket so the MFU rows stay honest
                bucket=step_bucket(max(group, key=step_flops)),
                mode="chain",
                precision=chain_rung or "default",
                flops_effective=sum(step_flops(st) for st in group),
                steps=len(group),
            ):
                out = run_chain_split(
                    xp, group, buffers, precision,
                    precision_mode=chain_rung,
                )
                if sync is not None:
                    sync(out)
            i = end
            continue
        step = steps[i]
        mode = policy.modes[i] if policy is not None else None
        precision_mode = policy.precision_mode(i) if policy is not None else None
        # tag + credit the arithmetic that actually runs: without a
        # policy the split path executes the env default (gauss, 0.75x
        # credit), never 'naive'; the complex (non-split) path is the
        # naive lowering
        resolved = (
            resolved_step_mode(step, mode) if split_complex else "naive"
        )
        if resolved == "fused_transpose":
            # the static gate can't see the live buffers: share the
            # kernel route's runtime dtype/batch predicate so spans
            # never credit a transpose pass that was actually paid
            # (kernel_error is the one remaining blind spot —
            # abnormal and counted)
            from tnc_tpu.ops.split_complex import (
                fused_transpose_runtime_ineligible_reason,
            )

            if (
                fused_transpose_runtime_ineligible_reason(
                    buffers[step.lhs], buffers[step.rhs], step
                )
                is not None
            ):
                resolved = "naive"
        # predicted traffic credits the prep pass the resolved kernel
        # actually pays: fused_transpose streams the macro transpose
        # inside the kernel, every other mode materializes it
        elems_in, elems_out = step_elems(step, mode=resolved)
        with obs.span(
            step_label(i, step),
            executor=executor,
            flops=step_flops(step),
            bytes_in=elems_in * dtype_bytes,
            bytes_out=elems_out * dtype_bytes,
            bucket=step_bucket(step),
            mode=resolved,
            precision=(precision_mode or "default"),
            flops_effective=effective_step_flops(step, resolved),
        ):
            out = kernel(
                buffers[step.lhs], buffers[step.rhs], step, mode,
                precision_mode,
            )
            if sync is not None:
                sync(out)
        buffers[step.lhs] = out
        buffers[step.rhs] = None  # free eagerly
        i += 1
    return buffers[program.result_slot]


# Locked: the distributed local phase compiles/executes per-partition
# programs from a thread pool (parallel/partitioned.py).
_PROGRAM_JIT_CACHE: "OrderedDict[tuple, Any]" = OrderedDict()
_PROGRAM_JIT_CACHE_MAX = 256
_PROGRAM_JIT_CACHE_LOCK = threading.Lock()


def lanemix_env() -> tuple:
    """The lanemix env knobs are read at *trace* time, so every compiled
    executable must be keyed by them — otherwise flipping
    ``TNC_TPU_LANEMIX`` mid-process silently returns stale programs."""
    return (
        os.environ.get("TNC_TPU_LANEMIX", "matmul"),
        os.environ.get("TNC_TPU_LANEMIX_MATMUL_MAX", "2048"),
    )


def jit_program(
    program: ContractionProgram,
    split_complex: bool,
    precision: str | None = None,
    donate: bool = True,
    batched: frozenset[int] | None = None,
    policy=None,
):
    """Program → jitted ``fn(buffers)`` with donated inputs; one traced
    function per (program, mode), one XLA executable per input placement.
    Shared by :class:`JaxBackend` and the distributed executors.
    LRU-bounded so long sweeps over many distinct networks don't pin
    every executable for the process lifetime.

    ``batched``: slots whose buffers carry a leading batch axis — the
    whole path is ``jax.vmap``-ed over them (amplitude sweeps,
    :meth:`JaxBackend.execute_batched`).

    ``policy``: a :class:`tnc_tpu.ops.split_complex.KernelPolicy` —
    the per-step kernel promotion ladder the trace bakes in (split
    mode only). Part of the cache key: two policies over the same
    program are different executables."""
    import jax

    from tnc_tpu.ops.split_complex import complex_mult_key, dot_precision_key

    if not split_complex:
        precision = None  # only the split path consumes it: one cache key
        policy = None
    key = (
        program.signature(),
        split_complex,
        precision,
        donate,
        lanemix_env(),
        complex_mult_key() if split_complex else None,
        # TNC_TPU_DOT_PRECISION is read at trace time (the per-step
        # precision resolve), so forced and auto traces must not share
        # an executable — complex_mult_key-style
        dot_precision_key() if split_complex else None,
        batched,
        policy.signature() if policy is not None else None,
    )
    with _PROGRAM_JIT_CACHE_LOCK:
        fn = _PROGRAM_JIT_CACHE.get(key)
        if fn is not None:
            _PROGRAM_JIT_CACHE.move_to_end(key)
    obs.counter_add("jit_cache.hit" if fn is not None else "jit_cache.miss")
    if fn is None:
        logger.debug(
            "jit: tracing program (%d steps, split_complex=%s)",
            len(program.steps),
            split_complex,
        )
        import jax.numpy as jnp

        if split_complex:
            from tnc_tpu.ops.split_complex import run_steps_split

            def run(buffers):
                return run_steps_split(
                    jnp, program, list(buffers), precision, policy=policy
                )

        else:

            def run(buffers):
                return _run_steps(jnp, program, list(buffers))

        if batched is not None:
            in_axis = (0, 0) if split_complex else 0
            axes = [
                in_axis if slot in batched else None
                for slot in range(program.num_inputs)
            ]
            run = jax.vmap(run, in_axes=(axes,))
        jitted = jax.jit(run, donate_argnums=(0,) if donate else ())
        n_steps = len(program.steps)
        first_call = [True]  # compile-vs-execute split for the trace

        def fn(buffers, _jitted=jitted):
            # transient runtime failures (preemption notice, ICI/DCN
            # hiccup) retry the dispatch under the shared policy; OOM and
            # genuine errors re-raise for the callers' degradation
            # ladders. The no-failure path costs one extra frame.
            def _dispatch():
                _faults.fault_point("backend.dispatch")
                out = _jitted(buffers)
                if _retry.sync_dispatch():
                    # surface async device failures inside this guarded
                    # region instead of at the next use of the result
                    jax.block_until_ready(out)
                return out

            def _run_with_retry():
                # the guard downgrades TRANSIENT to FATAL once a donating
                # dispatch consumed the inputs (retrying deleted arrays
                # would mask the original error)
                return _retry.default_policy().run(
                    _dispatch,
                    label="backend.dispatch",
                    classify=_retry.donation_guarded_classify(buffers),
                )

            with warnings.catch_warnings():
                # Tiny gate inputs routinely can't back larger intermediates;
                # XLA's per-buffer donation warning is pure noise here.
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable"
                )
                if not obs.enabled():
                    first_call[0] = False
                    return _run_with_retry()
                # first call of a traced program pays the XLA compile
                # (jax.jit is lazy); later calls are dispatch-only
                name = (
                    "backend.compile+dispatch"
                    if first_call[0]
                    else "backend.dispatch"
                )
                first_call[0] = False
                with obs.span(name, steps=n_steps):
                    return _run_with_retry()

        with _PROGRAM_JIT_CACHE_LOCK:
            _PROGRAM_JIT_CACHE[key] = fn
            while len(_PROGRAM_JIT_CACHE) > _PROGRAM_JIT_CACHE_MAX:
                _PROGRAM_JIT_CACHE.popitem(last=False)
    return fn


def place_buffers(
    arrays: Sequence[Any],
    dtype,
    split_complex: bool,
    device=None,
) -> list[Any]:
    """Host arrays → device buffers: complex arrays as-is, or (real, imag)
    float pairs in split mode. Shared by :class:`JaxBackend` and the
    distributed executors (the placement rule must not diverge)."""
    import jax
    import jax.numpy as jnp

    with obs.span("backend.place_buffers", n=len(arrays)):
        if split_complex:
            from tnc_tpu.ops.split_complex import split_array

            part_dtype = "float64" if "128" in str(dtype) else "float32"
            out = []
            for a in arrays:
                re, im = split_array(a, part_dtype)
                out.append(
                    (
                        jax.device_put(jnp.asarray(re), device),
                        jax.device_put(jnp.asarray(im), device),
                    )
                )
            return out
        return [
            jax.device_put(jnp.asarray(a, dtype=dtype), device) for a in arrays
        ]


class NumpyBackend(Backend):
    name = "numpy"

    def __init__(self, dtype=np.complex128):
        self.dtype = np.dtype(dtype)

    def execute(
        self,
        program: ContractionProgram,
        arrays: Sequence[Any],
        step_spans: bool | None = None,
    ) -> np.ndarray:
        """``step_spans``: per-step timing spans. Default (``None``) —
        on whenever tracing is on (the oracle is synchronous, so the
        timing is exact and costs no sync). Timed regions that must not
        carry span bookkeeping inside them (the bench CPU baseline)
        pass ``False`` explicitly."""
        buffers = [np.asarray(a, dtype=self.dtype) for a in arrays]
        if obs.enabled() and (step_spans is None or step_spans):
            out = run_steps_timed(
                np, program, buffers, float(self.dtype.itemsize)
            )
        else:
            out = _run_steps(np, program, buffers)
        return np.asarray(out).reshape(program.result_shape)

    def execute_batched(
        self,
        program: ContractionProgram,
        arrays: Sequence[Any],
        batched: Sequence[int],
    ) -> np.ndarray:
        """Host counterpart of :meth:`JaxBackend.execute_batched`: the
        slots in ``batched`` carry a leading ``(B, ...)`` axis, every
        other slot is shared. The batch leg is threaded through the
        step list (:mod:`tnc_tpu.ops.batched`) so each touched step
        runs as one stacked GEMM — per-entry results bit-compare to B
        sequential :meth:`execute` calls. Falls back to the sequential
        loop when a step cannot carry the leg. Returns ``(B,) +
        result_shape``. ``batched`` must name at least one slot — with
        none there is no batch axis to thread; use :meth:`execute`."""
        from tnc_tpu.ops.batched import (
            run_steps_batched,
            stacked_rows,
            thread_batch,
        )

        batched = list(batched)
        if not batched:
            raise ValueError(
                "execute_batched needs at least one batched slot; "
                "use execute() for unbatched programs"
            )
        b = int(np.asarray(arrays[batched[0]]).shape[0])
        flags, threadable = thread_batch(program, batched)
        if threadable:
            buffers = [np.asarray(a, dtype=self.dtype) for a in arrays]
            out = run_steps_batched(np, program, buffers, flags)
            return np.asarray(out).reshape((b,) + tuple(program.result_shape))
        return stacked_rows(
            lambda per: self.execute(program, per),
            list(arrays), batched, b, program.result_shape,
        )

    supports_slice_hooks = True

    def execute_sliced(
        self,
        sp,
        arrays: Sequence[Any],
        max_slices: int | None = None,
        host: bool = True,
        hoist: bool | None = None,
        slice_range: tuple[int, int] | None = None,
        ckpt: str | None = None,
        on_slice=None,
    ) -> np.ndarray:
        """``host=False`` mirrors the device backends' contract as far
        as it applies here (data is already host-resident): the result
        comes back in **stored** (merged) shape instead of
        ``result_shape``. ``hoist`` defaults to off — the naive loop
        is the oracle the hoisted executors are tested against.
        ``slice_range=(lo, hi)`` sums only that contiguous slice shard
        (the multi-host serving partial). ``ckpt`` / ``on_slice``
        (``supports_slice_hooks``): slice-boundary checkpointing and
        cooperative preemption — see
        :func:`~tnc_tpu.ops.sliced.execute_sliced_numpy`."""
        from tnc_tpu.ops.sliced import execute_sliced_numpy

        out = execute_sliced_numpy(
            sp, arrays, dtype=self.dtype, max_slices=max_slices,
            hoist=bool(hoist), slice_range=slice_range,
            ckpt=ckpt, on_slice=on_slice,
        )
        if not host:
            return out.reshape(sp.program.stored_result_shape)
        return out


class JaxBackend(Backend):
    """jit-compiled whole-path execution on the default JAX device.

    >>> import numpy as np
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    >>> from tnc_tpu.ops.program import build_program, flat_leaf_tensors
    >>> tn = CompositeTensor([
    ...     LeafTensor([0], [2], TensorData.matrix(np.array([1.0, 2.0]))),
    ...     LeafTensor([0], [2], TensorData.matrix(np.array([3.0, 4.0])))])
    >>> path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    >>> program = build_program(tn, path)
    >>> arrays = [l.data.into_data() for l in flat_leaf_tensors(tn)]
    >>> complex(JaxBackend(dtype="complex64").execute(program, arrays))
    (11+0j)
    >>> complex(NumpyBackend().execute(program, arrays))
    (11+0j)

    Off-CPU the backend automatically switches to split-complex mode
    (tensors as (real, imag) float pairs, Gauss 3-matmul contractions) —
    the TPU runtime has no complex dtypes (see
    :mod:`tnc_tpu.ops.split_complex`). ``precision`` controls the MXU
    matmul passes in split mode ('default' | 'float32' | 'highest').
    """

    name = "jax"

    def __init__(
        self,
        dtype="complex64",
        donate: bool = True,
        device=None,
        split_complex: bool | None = None,
        precision: str | None = "float32",
        sliced_strategy: str = "chunked",
        slice_batch: int = 8,
        chunk_steps: int = 64,
        loop_unroll: int = 1,
        hoist: bool = True,
    ):
        """``sliced_strategy``: 'chunked' (default) splits the program
        into slice-batched chunks (K small compiles, batched matmuls,
        HBM-budget-clamped batch — see :mod:`tnc_tpu.ops.chunked`);
        'loop' compiles the whole slice loop into one on-device
        ``fori_loop`` program. Measured on the v5e (north-star program):
        the straight-line chunked code runs the same steps ~150× faster
        than the while-loop body — XLA pessimizes loop bodies — so
        'loop' is only worth it when dispatch latency dominates (very
        small per-slice programs).

        ``hoist`` (default True): execute the slice-invariant stem once
        per call and loop only the residual program (see
        :mod:`tnc_tpu.ops.hoist`); degrades to the naive loop when every
        step depends on a sliced leg. Per-call overrides via
        ``execute_sliced(..., hoist=...)``."""
        import jax

        self._jax = jax
        self.dtype = dtype
        self.donate = donate
        self.device = device
        if split_complex is None:
            platform = (device or jax.devices()[0]).platform
            split_complex = platform != "cpu"
        self.split_complex = split_complex
        self.precision = precision
        if sliced_strategy not in ("loop", "chunked"):
            raise ValueError(f"unknown sliced_strategy {sliced_strategy!r}")
        self.sliced_strategy = sliced_strategy
        self.slice_batch = slice_batch
        self.chunk_steps = chunk_steps
        self.loop_unroll = loop_unroll
        self.hoist = hoist
        self._cache: dict[tuple, Any] = {}
        self._policy_cache: dict[tuple, Any] = {}

    def kernel_policy(self, program: ContractionProgram):
        """The kernel promotion ladder for ``program`` (split mode
        only; ``None`` otherwise): per-step naive/gauss/strassen modes
        plus fused multi-step chains, planned once per (program, env
        override) from the live calibrated cost model when one can be
        fitted (:meth:`tnc_tpu.obs.calibrate.CalibratedCostModel.
        from_registry`) and cached — the policy is part of the jit
        key, so it must not flap between calls as new step samples
        arrive."""
        if not self.split_complex:
            return None
        from tnc_tpu.ops.split_complex import (
            complex_mult_key,
            dot_precision_key,
            plan_kernels,
        )

        key = (program.signature(), complex_mult_key(), dot_precision_key())
        policy = self._policy_cache.get(key)
        if policy is None:
            cost_model = None
            try:
                from tnc_tpu.obs.calibrate import CalibratedCostModel

                cost_model = CalibratedCostModel.from_registry()
            except Exception:  # noqa: BLE001 — planning must not fail
                cost_model = None
            policy = plan_kernels(program, cost_model=cost_model)
            self._policy_cache[key] = policy
        return policy

    def _compiled(self, program: ContractionProgram):
        precision = self.precision if self.split_complex else None
        return jit_program(
            program, self.split_complex, precision, self.donate,
            policy=self.kernel_policy(program),
        )

    def _device_buffers(self, arrays: Sequence[Any]) -> list[Any]:
        return place_buffers(arrays, self.dtype, self.split_complex, self.device)

    def execute(self, program: ContractionProgram, arrays: Sequence[Any]) -> np.ndarray:
        buffers = self._device_buffers(arrays)
        result = self._run(program, buffers)
        if self.split_complex:
            from tnc_tpu.ops.split_complex import combine_array

            return combine_array(*result).reshape(program.result_shape)
        return np.asarray(result).reshape(program.result_shape)

    def _run(self, program: ContractionProgram, buffers: list[Any]):
        if obs.enabled() and obs.step_timing_enabled():
            # TNC_TPU_STEP_TIME: eager op-by-op execution, blocking on
            # each step's result — every step span carries a true
            # measured device time next to its predicted flops/bytes
            # (the calibration input). Orders of magnitude slower than
            # the compiled path; never on by default.
            import jax
            import jax.numpy as jnp

            return run_steps_timed(
                jnp,
                program,
                list(buffers),
                dtype_bytes=dtype_width(self.dtype),
                split_complex=self.split_complex,
                precision=self.precision,
                sync=jax.block_until_ready,
                policy=self.kernel_policy(program),
            )
        return self._compiled(program)(buffers)

    def execute_sliced(
        self,
        sp,
        arrays: Sequence[Any],
        max_slices: int | None = None,
        host: bool = True,
        hoist: bool | None = None,
        slice_range: tuple[int, int] | None = None,
    ):
        """Run a sliced program; the slice loop executes on device.
        ``max_slices`` caps the loop (partial sum — benchmark subsets).
        ``host=False`` keeps the result on device in stored shape (a
        (real, imag) pair in split mode) — no device→host transfer, the
        benchmark-timing contract (tunneled backends degrade dispatch
        permanently after the first D2H; see TPU_EVIDENCE_r03.md).
        ``hoist`` overrides the backend default (slice-invariant stem
        executed once, residual looped — :mod:`tnc_tpu.ops.hoist`).
        ``slice_range=(lo, hi)`` sums only that contiguous slice shard
        on device (the multi-host serving partial) — under the
        backend's own sliced strategy: chunked runs the range through
        the chunked executor, the loop strategies compile a range-bound
        loop program."""

        from tnc_tpu.ops.sliced import make_jax_sliced_fn

        if hoist is None:
            hoist = self.hoist
        obs.counter_add(
            "backend.execute_sliced_calls", strategy=self.sliced_strategy
        )
        if slice_range is not None:
            if max_slices is not None:
                raise ValueError(
                    "slice_range and max_slices are exclusive"
                )
            if self.sliced_strategy == "chunked" and sp.slicing.num_slices > 1:
                # keep the fast path: on real TPUs the chunked executor
                # is the tuned strategy (~150x per slice vs the loop
                # program, docs/running_on_tpu.md) — a range shard must
                # not silently demote every serving host to the loop
                from tnc_tpu.ops.chunked import execute_sliced_batched_jax

                return execute_sliced_batched_jax(
                    sp,
                    arrays,
                    batch=self.slice_batch,
                    chunk_steps=self.chunk_steps,
                    split_complex=self.split_complex,
                    precision=self.precision,
                    dtype=self.dtype,
                    device=self.device,
                    host=host,
                    hoist=hoist,
                    slice_range=tuple(slice_range),
                )
            from tnc_tpu.ops.split_complex import (
                complex_mult_key,
                dot_precision_key,
            )

            key = (
                "sliced_range", sp.signature(), str(self.dtype),
                self.split_complex, tuple(slice_range), hoist,
                lanemix_env(),
                complex_mult_key() if self.split_complex else None,
                dot_precision_key() if self.split_complex else None,
            )
            fn = self._cache.get(key)
            if fn is None:
                fn = make_jax_sliced_fn(
                    sp,
                    split_complex=self.split_complex,
                    precision=self.precision,
                    hoist=hoist,
                    slice_range=tuple(slice_range),
                )
                self._cache[key] = fn
            result = fn(self._device_buffers(arrays))
            if not host:
                return result
            if self.split_complex:
                from tnc_tpu.ops.split_complex import combine_array

                return combine_array(*result).reshape(
                    sp.program.result_shape
                )
            return np.asarray(result).reshape(sp.program.result_shape)
        if sp.slicing.num_slices == 1:
            if not host:  # device-resident, stored shape — no D2H
                return self.execute_on_device(sp.program, arrays)
            return self.execute(sp.program, arrays)

        if self.sliced_strategy == "chunked":
            from tnc_tpu.ops.chunked import execute_sliced_batched_jax

            return execute_sliced_batched_jax(
                sp,
                arrays,
                batch=self.slice_batch,
                chunk_steps=self.chunk_steps,
                split_complex=self.split_complex,
                precision=self.precision,
                dtype=self.dtype,
                device=self.device,
                max_slices=max_slices,
                host=host,
                hoist=hoist,
            )

        from tnc_tpu.ops.split_complex import complex_mult_key, dot_precision_key

        key = (
            "sliced",
            sp.signature(),
            str(self.dtype),
            self.split_complex,
            max_slices,
            self.loop_unroll,
            hoist,
            lanemix_env(),
            complex_mult_key() if self.split_complex else None,
            dot_precision_key() if self.split_complex else None,
        )
        fn = self._cache.get(key)
        if fn is None:
            fn = make_jax_sliced_fn(
                sp,
                split_complex=self.split_complex,
                precision=self.precision,
                num_slices=max_slices,
                unroll=self.loop_unroll,
                hoist=hoist,
            )
            self._cache[key] = fn
        buffers = self._device_buffers(arrays)
        result = fn(buffers)
        if not host:
            return result
        if self.split_complex:
            from tnc_tpu.ops.split_complex import combine_array

            return combine_array(*result).reshape(sp.program.result_shape)
        return np.asarray(result).reshape(sp.program.result_shape)

    def execute_batched(
        self,
        program: ContractionProgram,
        arrays: Sequence[Any],
        batched: Sequence[int],
    ) -> np.ndarray:
        """Run ``program`` once over a leading batch axis carried by the
        slots in ``batched`` (their arrays are stacked ``(B, ...)``;
        every other slot is shared). The whole path is ``jax.vmap``-ed
        and jitted once — B network evaluations for one compile and one
        dispatch, the TPU-native shape for amplitude sweeps
        (:mod:`tnc_tpu.tensornetwork.sweep`). Returns ``(B,) +
        result_shape``."""
        precision = self.precision if self.split_complex else None
        fn = jit_program(
            program,
            self.split_complex,
            precision,
            self.donate,
            batched=frozenset(batched),
            policy=self.kernel_policy(program),
        )
        buffers = self._device_buffers(arrays)
        result = fn(buffers)
        if self.split_complex:
            from tnc_tpu.ops.split_complex import combine_array

            out = combine_array(*result)
        else:
            out = np.asarray(result)
        return out.reshape((-1,) + tuple(program.result_shape))

    def execute_on_device(self, program: ContractionProgram, arrays: Sequence[Any]):
        """Like :meth:`execute` but leaves the result on device (no host
        round-trip; a (real, imag) pair in split mode) — used for
        benchmarking and distributed fan-in. The buffer is in **stored**
        shape (``program.stored_result_shape``) with axes in
        ``program.result_legs`` order, not ``result_shape``/canonical
        order — reshape/permute host-side when leg semantics matter.
        """
        return self._run(program, self._device_buffers(arrays))

    def bind_resident(self, program: ContractionProgram, arrays: Sequence[Any]):
        """Stage ``arrays`` to the device once and return a zero-transfer
        callable: each call re-dispatches the compiled program on the
        resident input buffers and returns the device-resident result
        (stored shape; a (real, imag) pair in split mode).

        Donation is disabled for the bound executable so the resident
        inputs survive arbitrarily many calls — this is the steady-state
        evaluation shape (gate tensors live in HBM, only the dispatch
        recurs), the analogue of the reference's timed contraction region
        which starts after data placement
        (``benchmark/src/main.rs:355-405``).
        """
        precision = self.precision if self.split_complex else None
        fn = jit_program(
            program, self.split_complex, precision, donate=False,
            policy=self.kernel_policy(program),
        )
        buffers = self._device_buffers(arrays)
        return lambda: fn(buffers)


_BACKENDS: dict[str, Backend] = {}


def get_backend(name: str | Backend | None = None) -> Backend:
    """Resolve a backend by name ('numpy', 'jax'), instance, or default."""
    if isinstance(name, Backend):
        return name
    if name is None:
        name = "numpy"
    backend = _BACKENDS.get(name)
    if backend is None:
        if name == "numpy":
            backend = NumpyBackend()
        elif name == "jax":
            backend = JaxBackend()
        elif name == "jax64":
            backend = JaxBackend(dtype="complex128")
        else:
            raise ValueError(f"Unknown backend '{name}'")
        _BACKENDS[name] = backend
    return backend
