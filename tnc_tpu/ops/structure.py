"""Structured-leaf analysis: how much gate structure a plan could exploit.

Gate tensors are rarely dense: CZ/CP are diagonal, CX/SWAP/X are
permutations, many single-qubit gates are monomial (one nonzero per
row/column). A contraction step against such an operand needs no MXU
matmul at all — a diagonal contraction is an elementwise broadcast
multiply, a permutation contraction a gather. This module MEASURES that
opportunity (docs/future_work.md item 6) without touching the executor:
:func:`program_structure_report` classifies every leaf and attributes
the program's step flops to the strongest structure class involved
(contracting against a diagonal operand is elementwise no matter what
the other side is), giving the honest ceiling for a structure-aware
compiler.

Classification is on materialized data (exact zero tests with a relative
tolerance), so user-supplied matrices classify identically to registry
gates.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor

#: structure classes, strongest (cheapest to contract) first
CLASSES = ("identity_scaled", "permutation_scaled", "diagonal", "monomial", "dense")


def classify_array(arr, tol: float = 1e-12) -> str:
    """Structure class of a (gate-like) tensor, viewed as a matrix over
    its balanced in/out split. Odd-rank or unbalanced tensors (vectors,
    rectangular maps) classify as 'dense' — a contraction against them
    is never one of the cheap special cases.

    >>> import numpy as np
    >>> classify_array(np.diag([1.0, 2.0]))
    'diagonal'
    >>> classify_array(np.array([[0.0, 1.0], [1.0, 0.0]]))  # X gate
    'permutation_scaled'
    >>> classify_array(np.ones((2, 2)) / 2)
    'dense'
    """
    a = np.asarray(arr)
    if a.ndim < 2 or a.ndim % 2 != 0:
        return "dense"
    half = a.ndim // 2
    rows = int(np.prod(a.shape[:half]))
    cols = int(np.prod(a.shape[half:]))
    if rows != cols:
        return "dense"
    side = rows
    m = a.reshape(side, side)
    scale = float(np.max(np.abs(m)))
    if scale == 0.0:
        return "diagonal"
    t = tol * scale
    nz = np.abs(m) > t
    row_counts = nz.sum(axis=1)
    col_counts = nz.sum(axis=0)
    eye = np.eye(side, dtype=bool)
    if np.all(nz == eye):
        diag = np.diag(m)
        # identity requires equal complex VALUES, not just magnitudes
        # (CZ/T/RZ are diagonal-with-phases, not c*I)
        if np.all(np.abs(diag - diag[0]) <= t):
            return "identity_scaled"
        return "diagonal"
    if np.all(nz == np.diag(np.diag(nz))):
        return "diagonal"
    if np.all(row_counts <= 1) and np.all(col_counts <= 1):
        vals = m[nz]
        # c*P needs one shared complex value; differing phases (iSWAP)
        # make it a general monomial D*P
        if (
            np.all(row_counts == 1)
            and np.all(col_counts == 1)
            and np.all(np.abs(vals - vals[0]) <= t)
        ):
            return "permutation_scaled"
        return "monomial"
    return "dense"


@dataclass
class StructureReport:
    leaf_classes: dict[str, int]
    step_flops: dict[str, float]
    total_flops: float

    @property
    def exploitable_fraction(self) -> float:
        """Fraction of step flops whose weaker operand is structured
        (non-dense) — the ceiling a structure-aware step compiler could
        remove from the MXU."""
        if self.total_flops <= 0:
            return 0.0
        dense = self.step_flops.get("dense", 0.0)
        return 1.0 - dense / self.total_flops


def program_structure_report(
    tn: CompositeTensor, replace_path, tol: float = 1e-12
) -> StructureReport:
    """Classify every leaf and attribute each step's naive flops to the
    STRONGEST class among its two operands — contracting against a
    diagonal operand is elementwise no matter what the other side is
    (an intermediate counts as dense: structure rarely survives a
    contraction). The result is a ceiling, not a plan: leg alignment
    decides what a compiler could actually lower."""
    from tnc_tpu.contractionpath.contraction_cost import contract_cost_tensors
    from tnc_tpu.ops.program import flat_leaf_tensors
    from tnc_tpu.tensornetwork.tensordata import DataKind

    leaves = flat_leaf_tensors(tn)
    if len(list(tn.tensors)) != len(leaves):
        # replace-path indices address TOP-LEVEL slots (composites
        # collapse to one); indexing them into the flat leaf list would
        # silently misattribute — same guard as flat_replace_path
        raise ValueError(
            "program_structure_report expects a flat network/path; "
            "flatten partitioned networks first"
        )
    classes: list[str] = []
    counts: dict[str, int] = {c: 0 for c in CLASSES}
    for leaf in leaves:
        if leaf.data.kind is DataKind.NONE:
            cls = "dense"  # metadata-only: assume nothing
        else:
            cls = classify_array(leaf.data.into_data(), tol)
        classes.append(cls)
        counts[cls] += 1

    order = {c: i for i, c in enumerate(CLASSES)}
    tensors = list(leaves)  # slots are rebound, never mutated
    step_flops: dict[str, float] = {c: 0.0 for c in CLASSES}
    total = 0.0
    for i, j in replace_path:
        ti, tj = tensors[i], tensors[j]
        flops = contract_cost_tensors(ti, tj)
        # the stronger operand decides: a dense x diagonal step is an
        # elementwise multiply, dense x dense needs the MXU
        best = min(classes[i], classes[j], key=lambda c: order[c])
        step_flops[best] += flops
        total += flops
        tensors[i] = ti ^ tj
        classes[i] = "dense"
    return StructureReport(counts, step_flops, total)
