"""Fused split-complex matmul as a Pallas TPU kernel.

The split-complex step kernel lowers a complex contraction to 4 real
dots (naive) or 3 dots + 5 elementwise passes (Gauss) — either way XLA
emits *separate* MXU ops whose operands each travel HBM→VMEM twice (ar
feeds two products, br feeds two products, …) plus an elementwise
epilogue over full-size outputs. This kernel computes both outputs in
one pass:

    re = arᵀ·br − aiᵀ·bi
    im = arᵀ·bi + aiᵀ·br

with each operand tile loaded into VMEM **once** per grid cell and both
accumulators living in VMEM scratch across the K loop — roughly halving
operand HBM traffic on bandwidth-bound steps and deleting the epilogue
passes entirely (docs/future_work.md item 2; the MFU-attribution work of
VERDICT r3 #4).

Layout: operands arrive exactly as the program compiler's dot layout
produces them — contract-dim-leading 2-D views ``A:(K, M)``,
``B:(K, N)`` (the ``cfirst`` orientation; other orientations fall back
to the plain naive path). Tile sizes respect the f32 (8, 128) minimum
and shapes must divide their tiles (program dims are powers of two, so
any dim ≥ the tile divides it; smaller/ragged shapes fall back).

Selected with ``TNC_TPU_COMPLEX_MULT=fused``; correctness is pinned in
interpret mode on CPU (tests/test_pallas_complex.py) and the hardware
A/B runs in ``scripts/hw_campaign.sh``. Meant to be called inside an
outer ``jax.jit`` (every executor's step kernel already is).
"""

from __future__ import annotations

MIN_FLOPS = 1 << 22  # below this the dispatch/grid overhead dominates


def _tile(dim: int, cap: int, floor: int) -> int | None:
    """Largest tile ≤ cap that divides ``dim`` and is ≥ floor."""
    t = min(cap, dim)
    while t >= floor:
        if dim % t == 0:
            return t
        t //= 2
    return None


def eligible(k: int, m: int, n: int) -> bool:
    """Can the fused kernel run this (K,M)x(K,N) problem profitably?

    >>> eligible(512, 1024, 1024)   # big power-of-two problem: yes
    True
    >>> eligible(4, 4, 4)           # under MIN_FLOPS and tile floors
    False
    """
    if 2 * k * m * n < MIN_FLOPS:
        return False
    return (
        _tile(m, 128, 8) is not None
        and _tile(n, 128, 128) is not None
        and _tile(k, 512, 8) is not None
    )


def fused_complex_dot_kl(ar, ai, br, bi, interpret: bool = False,
                         precision=None):
    """``(re, im)`` of the complex product ``(ar+i·ai)ᵀ · (br+i·bi)``.

    ``ar, ai: (K, M)``; ``br, bi: (K, N)``; outputs ``(M, N)`` float32.
    ``precision`` is the ``lax.Precision`` for the tile dots — callers
    on the f32 parity contract must pass HIGHEST (MXU default would run
    bf16-multiply passes and miss the 1e-5 target by orders of
    magnitude; invisible in interpret mode, which is always full f32).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    k, m = ar.shape
    _, n = br.shape
    tm = _tile(m, 128, 8)
    tn = _tile(n, 128, 128)
    tk = _tile(k, 512, 8)
    if tm is None or tn is None or tk is None:
        raise ValueError(f"shape (K={k}, M={m}, N={n}) not tileable")

    def kernel(ar_ref, ai_ref, br_ref, bi_ref, re_ref, im_ref, racc, iacc):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _init():
            racc[:] = jnp.zeros_like(racc)
            iacc[:] = jnp.zeros_like(iacc)

        dims = (((0,), (0,)), ((), ()))

        def dot(x, y):
            return jax.lax.dot_general(
                x, y, dims,
                precision=precision,
                preferred_element_type=jnp.float32,
            )

        art, ait = ar_ref[:], ai_ref[:]
        brt, bit = br_ref[:], bi_ref[:]
        racc[:] += dot(art, brt) - dot(ait, bit)
        iacc[:] += dot(art, bit) + dot(ait, brt)

        @pl.when(kk == pl.num_programs(2) - 1)
        def _flush():
            re_ref[:] = racc[:]
            im_ref[:] = iacc[:]

    a_spec = pl.BlockSpec((tk, tm), lambda i, j, kk: (kk, i))
    b_spec = pl.BlockSpec((tk, tn), lambda i, j, kk: (kk, j))
    out_spec = pl.BlockSpec((tm, tn), lambda i, j, kk: (i, j))
    f32 = jnp.float32
    return pl.pallas_call(
        kernel,
        grid=(m // tm, n // tn, k // tk),
        in_specs=[a_spec, a_spec, b_spec, b_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), f32),
            jax.ShapeDtypeStruct((m, n), f32),
        ],
        scratch_shapes=_scratch((tm, tn), f32),
        interpret=interpret,
    )(ar, ai, br, bi)


def _scratch(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.VMEM(shape, dtype), pltpu.VMEM(shape, dtype)]
