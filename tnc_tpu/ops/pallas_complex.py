"""Fused split-complex matmuls as Pallas TPU kernels.

The split-complex step kernel lowers a complex contraction to 4 real
dots (naive) or 3 dots + 5 elementwise passes (Gauss) — either way XLA
emits *separate* MXU ops whose operands each travel HBM→VMEM twice (ar
feeds two products, br feeds two products, …) plus an elementwise
epilogue over full-size outputs. This kernel computes both outputs in
one pass:

    re = arᵀ·br − aiᵀ·bi
    im = arᵀ·bi + aiᵀ·br

with each operand tile loaded into VMEM **once** per grid cell and both
accumulators living in VMEM scratch across the K loop — roughly halving
operand HBM traffic on bandwidth-bound steps and deleting the epilogue
passes entirely (docs/future_work.md item 2; the MFU-attribution work of
VERDICT r3 #4).

Layout: operands arrive exactly as the program compiler's dot layout
produces them — contract-dim-leading 2-D views ``A:(K, M)``,
``B:(K, N)`` (the ``cfirst`` orientation; other orientations fall back
to the plain naive path). Tile sizes respect the f32 (8, 128) minimum
and shapes must divide their tiles (program dims are powers of two, so
any dim ≥ the tile divides it; smaller/ragged shapes fall back).

Selected with ``TNC_TPU_COMPLEX_MULT=fused``; correctness is pinned in
interpret mode on CPU (tests/test_pallas_complex.py) and the hardware
A/B runs in ``scripts/hw_campaign.sh``. Meant to be called inside an
outer ``jax.jit`` (every executor's step kernel already is).

This module also carries the **fused multi-step chain kernel**
(:func:`fused_chain_kl`): a run of consecutive small residual PairSteps
— grouped by :func:`tnc_tpu.ops.program.chain_groups` because each
step's output feeds the next and everything fits VMEM — executes as ONE
``pallas_call``, every intermediate living in VMEM values, so the chain
pays the per-dispatch overhead (the calibrated ``dispatch_overhead_s``
that dominates small networks) once instead of per step.

The third kernel is the **fused transpose-matmul**
(:func:`fused_transpose_dot_kl`): transpose-dominated steps normally
pay a *materialized* macro transpose (``_prep_operand``'s
``xp.transpose`` — one full HBM read + write per permuted operand)
before the dot reads the operand again. This kernel takes the operands
in their RAW stored macro views and applies the permutation in the
``BlockSpec`` index maps — each HBM tile is fetched once, already in
dot order, and streamed straight into the MXU — so the transpose pass
disappears from HBM entirely (docs/future_work.md item 2).
:func:`fused_transpose_reference` replays the identical grid with the
identical per-tile body (:func:`_transpose_tile_dot`) as plain jax
ops — the bit-parity oracle proving the kernel changed streaming
structure only.
"""

from __future__ import annotations

import math

MIN_FLOPS = 1 << 22  # below this the dispatch/grid overhead dominates

#: VMEM budget for a fused chain, in float32 elements summed over every
#: operand and intermediate the chain touches ((real, imag) pairs count
#: double, so this bounds the real VMEM bytes at 4·CHAIN_MAX_ELEMS =
#: 4 MiB of the ~16 MiB/core — generous headroom for the compiler's
#: own staging).
CHAIN_MAX_ELEMS = 1 << 20


def _tile(dim: int, cap: int, floor: int) -> int | None:
    """Largest tile ≤ cap that divides ``dim`` and is ≥ floor."""
    t = min(cap, dim)
    while t >= floor:
        if dim % t == 0:
            return t
        t //= 2
    return None


def ineligible_reason(k: int, m: int, n: int) -> str | None:
    """Why the single-step fused kernel cannot run a (K,M)x(K,N)
    problem profitably — ``None`` when it can. The reason string is the
    label the ``ops.fused_fallback`` counter and the fallback warning
    carry, so bench records say *why* fused didn't fire.

    >>> ineligible_reason(512, 1024, 1024) is None
    True
    >>> ineligible_reason(4, 4, 4)
    'flop_floor'
    >>> ineligible_reason(1024, 4, 1024)   # M below the f32 sublane tile
    'tile_floor'
    """
    if 2 * k * m * n < MIN_FLOPS:
        return "flop_floor"
    if (
        _tile(m, 128, 8) is None
        or _tile(n, 128, 128) is None
        or _tile(k, 512, 8) is None
    ):
        return "tile_floor"
    return None


def eligible(k: int, m: int, n: int) -> bool:
    """Can the fused kernel run this (K,M)x(K,N) problem profitably?

    >>> eligible(512, 1024, 1024)   # big power-of-two problem: yes
    True
    >>> eligible(4, 4, 4)           # under MIN_FLOPS and tile floors
    False
    """
    return ineligible_reason(k, m, n) is None


def fused_complex_dot_kl(ar, ai, br, bi, interpret: bool = False,
                         precision=None):
    """``(re, im)`` of the complex product ``(ar+i·ai)ᵀ · (br+i·bi)``.

    ``ar, ai: (K, M)``; ``br, bi: (K, N)``; outputs ``(M, N)`` float32.
    ``precision`` is the ``lax.Precision`` for the tile dots — callers
    on the f32 parity contract must pass HIGHEST (MXU default would run
    bf16-multiply passes and miss the 1e-5 target by orders of
    magnitude; invisible in interpret mode, which is always full f32).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    k, m = ar.shape
    _, n = br.shape
    tm = _tile(m, 128, 8)
    tn = _tile(n, 128, 128)
    tk = _tile(k, 512, 8)
    if tm is None or tn is None or tk is None:
        raise ValueError(f"shape (K={k}, M={m}, N={n}) not tileable")

    def kernel(ar_ref, ai_ref, br_ref, bi_ref, re_ref, im_ref, racc, iacc):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _init():
            racc[:] = jnp.zeros_like(racc)
            iacc[:] = jnp.zeros_like(iacc)

        dims = (((0,), (0,)), ((), ()))

        def dot(x, y):
            return jax.lax.dot_general(
                x, y, dims,
                precision=precision,
                preferred_element_type=jnp.float32,
            )

        art, ait = ar_ref[:], ai_ref[:]
        brt, bit = br_ref[:], bi_ref[:]
        racc[:] += dot(art, brt) - dot(ait, bit)
        iacc[:] += dot(art, bit) + dot(ait, brt)

        @pl.when(kk == pl.num_programs(2) - 1)
        def _flush():
            re_ref[:] = racc[:]
            im_ref[:] = iacc[:]

    a_spec = pl.BlockSpec((tk, tm), lambda i, j, kk: (kk, i))
    b_spec = pl.BlockSpec((tk, tn), lambda i, j, kk: (kk, j))
    out_spec = pl.BlockSpec((tm, tn), lambda i, j, kk: (i, j))
    f32 = jnp.float32
    return pl.pallas_call(
        kernel,
        grid=(m // tm, n // tn, k // tk),
        in_specs=[a_spec, a_spec, b_spec, b_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), f32),
            jax.ShapeDtypeStruct((m, n), f32),
        ],
        scratch_shapes=_scratch((tm, tn), f32),
        interpret=interpret,
    )(ar, ai, br, bi)


def _scratch(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.VMEM(shape, dtype), pltpu.VMEM(shape, dtype)]


# -- fused transpose-matmul ---------------------------------------------


class OperandLayout:
    """Static HBM layout of one dot operand for the fused
    transpose-matmul kernel: how the RAW stored macro view maps onto
    the logical contract-dim-leading 2-D ``(K, F)`` matrix the dot
    consumes.

    ``view``: the stored macro view shape (the compiler's ``a_view`` /
    ``b_view``). ``k_axes`` / ``f_axes``: stored axis ids whose dims
    merge into the flat contract (``K``) and free (``F``) index, each
    listed most-significant digit first — i.e. in *permuted* order, so
    decomposing a flat index over them recovers the stored coordinates
    without ever materializing the transpose.
    """

    __slots__ = ("view", "k_axes", "f_axes")

    def __init__(self, view, k_axes, f_axes):
        self.view = tuple(int(d) for d in view)
        self.k_axes = tuple(int(a) for a in k_axes)
        self.f_axes = tuple(int(a) for a in f_axes)

    @property
    def kd(self) -> int:
        """Stored axis carrying the fastest-varying contract digit —
        the axis the k tile slides along."""
        return self.k_axes[-1]

    @property
    def fd(self) -> int:
        """Stored axis carrying the fastest-varying free digit."""
        return self.f_axes[-1]

    @property
    def k_size(self) -> int:
        return int(math.prod(self.view[a] for a in self.k_axes))

    @property
    def f_size(self) -> int:
        return int(math.prod(self.view[a] for a in self.f_axes))


def operand_layout(view, perm, dot_shape, cfirst) -> OperandLayout | None:
    """Derive an :class:`OperandLayout` from a PairStep operand's
    compiler fields, or ``None`` when the flat contract dim is not an
    exact run of permuted macro axes (``k = 1``, free side empty, or a
    contract dim straddling a fused run — none occur for
    compiler-built steps, but the gate must not trust that).

    >>> lay = operand_layout((4, 8, 128), (1, 0, 2), (8, 4, 128), True)
    >>> lay.k_axes, lay.f_axes          # k = axis 1 (dim 8), frees (4, 128)
    ((1,), (0, 2))
    >>> operand_layout((4, 8), None, (4, 8), True).k_axes
    (0,)
    >>> operand_layout((4, 8), None, (1, 32), True) is None   # k == 1
    True
    """
    view = tuple(int(d) for d in view)
    n = len(view)
    order = tuple(perm) if perm is not None else tuple(range(n))
    if sorted(order) != list(range(n)):
        return None
    k = int(dot_shape[0] if cfirst else dot_shape[-1])
    if cfirst:
        k_axes: list[int] = []
        prod = 1
        i = 0
        while prod < k and i < n:
            prod *= view[order[i]]
            k_axes.append(order[i])
            i += 1
        if prod != k:
            return None
        f_axes = list(order[i:])
    else:
        rev: list[int] = []
        prod = 1
        i = n - 1
        while prod < k and i >= 0:
            prod *= view[order[i]]
            rev.append(order[i])
            i -= 1
        if prod != k:
            return None
        k_axes = list(reversed(rev))
        f_axes = list(order[: i + 1])
    if not k_axes or not f_axes:
        return None
    return OperandLayout(view, k_axes, f_axes)


def _plan_transpose_tiles(
    a_lay: OperandLayout, b_lay: OperandLayout
) -> tuple[int, int, int] | None:
    """``(tm, tn, tk)`` tile sizes for one fused transpose-dot, or
    ``None`` when the active dims can't tile. The k tile must divide
    BOTH operands' fastest contract dims (the grid's k step covers the
    same flat-k range in each); free tiles follow the single-step
    kernel's floors (output minor dim keeps the 128-lane floor)."""
    tm = _tile(a_lay.view[a_lay.fd], 128, 8)
    tn = _tile(b_lay.view[b_lay.fd], 128, 128)
    tka = _tile(a_lay.view[a_lay.kd], 512, 8)
    tkb = _tile(b_lay.view[b_lay.kd], 512, 8)
    if tm is None or tn is None or tka is None or tkb is None:
        return None
    tk = math.gcd(tka, tkb)
    if tk < 8:
        return None
    return tm, tn, tk


def transpose_dot_ineligible_reason(
    a_lay: OperandLayout | None,
    b_lay: OperandLayout | None,
    k: int,
    m: int,
    n: int,
) -> str | None:
    """Why :func:`fused_transpose_dot_kl` cannot run this step —
    ``None`` when it can. Reason strings label the
    ``ops.fused_transpose_fallback`` counter:

    - ``layout``: a flat dim is not an exact run of permuted macro
      axes (``k = 1`` degenerates here);
    - ``flop_floor``: under :data:`MIN_FLOPS` — dispatch/grid overhead
      would dominate;
    - ``minor_axes``: the sliding tiles are not the two stored minor
      axes — leading-axis tiles would stream badly-tiled (sub-lane)
      blocks;
    - ``tile_floor``: an active dim has no tile ≥ its floor
      (non-tile-multiple perms land here).
    """
    if a_lay is None or b_lay is None:
        return "layout"
    if 2 * k * m * n < MIN_FLOPS:
        return "flop_floor"
    for lay in (a_lay, b_lay):
        nax = len(lay.view)
        if {lay.kd, lay.fd} != {nax - 2, nax - 1}:
            return "minor_axes"
    if _plan_transpose_tiles(a_lay, b_lay) is None:
        return "tile_floor"
    return None


def _transpose_tile_dot(ar, ai, br, bi, ka: int, kb: int, precision):
    """Per-tile arithmetic of the fused transpose-dot — the naive
    4-real-dot complex lowering on one (a-tile, b-tile) pair, with each
    tile in its STORED orientation (``ka``/``kb`` name the contract
    axis of each tile; the MXU takes either orientation natively).
    Shared verbatim by the Pallas kernel body and
    :func:`fused_transpose_reference`, so the kernel can only change
    streaming structure, never a bit."""
    import jax
    import jax.numpy as jnp

    dims = (((ka,), (kb,)), ((), ()))

    def dot(x, y):
        return jax.lax.dot_general(
            x, y, dims, precision=precision,
            preferred_element_type=jnp.float32,
        )

    return (
        dot(ar, br) - dot(ai, bi),
        dot(ar, bi) + dot(ai, br),
    )


def _transpose_block_geometry(a_lay, b_lay, tm, tn, tk):
    """Shared grid/block geometry: block shapes (stored order), the
    per-axis index radices each flat grid coordinate decomposes over,
    and the contract axis of each squeezed 2-D tile."""

    def one(lay, tf, tkk):
        nax = len(lay.view)
        block = [1] * nax
        block[lay.kd] = tkk
        block[lay.fd] = tf
        f_rad = [lay.view[ax] for ax in lay.f_axes[:-1]] + [
            lay.view[lay.fd] // tf
        ]
        k_rad = [lay.view[ax] for ax in lay.k_axes[:-1]] + [
            lay.view[lay.kd] // tkk
        ]
        # squeezed tile keeps the two stored-minor axes in stored order
        k_axis = 0 if lay.kd < lay.fd else 1
        tile2 = (tkk, tf) if k_axis == 0 else (tf, tkk)
        return tuple(block), f_rad, k_rad, k_axis, tile2

    return one(a_lay, tm, tk), one(b_lay, tn, tk)


def _decompose(idx, axes, radices, coords):
    """Write the mixed-radix digits of ``idx`` over ``axes`` (most
    significant first) into ``coords``. Works on python ints and traced
    scalars alike."""
    for ax, rad in zip(reversed(axes), reversed(radices)):
        coords[ax] = idx % rad
        idx = idx // rad


def fused_transpose_dot_kl(
    ar, ai, br, bi,
    a_layout: OperandLayout,
    b_layout: OperandLayout,
    interpret: bool = False,
    precision=None,
):
    """``(re, im)`` of the complex dot with BOTH operands' macro-dim
    permutations applied while streaming tiles into the MXU.

    ``ar, ai`` / ``br, bi``: the operands' RAW stored macro views
    (``a_layout.view`` / ``b_layout.view``-shaped float32 arrays — NOT
    pre-transposed). Outputs are the flat ``(M, N)`` float32 pair, rows
    iterating the first operand's free digits, columns the second's —
    exactly the prep+dot path's output order, so callers reshape to
    ``out_store`` unchanged. Each operand element crosses HBM once; the
    materialized transpose pass (read + write of the whole operand) the
    prep path pays is gone.

    Arithmetic is the naive 4-real-dot lowering accumulated in f32 VMEM
    scratch over the k grid — the same error contract as
    :func:`fused_complex_dot_kl`.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    tiles = _plan_transpose_tiles(a_layout, b_layout)
    if tiles is None:
        raise ValueError(
            f"layouts not tileable: {a_layout.view} / {b_layout.view}"
        )
    tm, tn, tk = tiles
    mm, nn = a_layout.f_size, b_layout.f_size
    kk_total = a_layout.k_size
    if b_layout.k_size != kk_total:
        raise ValueError("operand contract sizes disagree")
    (a_block, a_frad, a_krad, ka, a_tile2), (
        b_block, b_frad, b_krad, kb, b_tile2,
    ) = _transpose_block_geometry(a_layout, b_layout, tm, tn, tk)

    def a_map(i, j, kk):
        coords = [0] * len(a_block)
        _decompose(i, a_layout.f_axes, a_frad, coords)
        _decompose(kk, a_layout.k_axes, a_krad, coords)
        return tuple(coords)

    def b_map(i, j, kk):
        coords = [0] * len(b_block)
        _decompose(j, b_layout.f_axes, b_frad, coords)
        _decompose(kk, b_layout.k_axes, b_krad, coords)
        return tuple(coords)

    def kernel(ar_ref, ai_ref, br_ref, bi_ref, re_ref, im_ref, racc, iacc):
        kidx = pl.program_id(2)

        @pl.when(kidx == 0)
        def _init():
            racc[:] = jnp.zeros_like(racc)
            iacc[:] = jnp.zeros_like(iacc)

        art = ar_ref[:].reshape(a_tile2)
        ait = ai_ref[:].reshape(a_tile2)
        brt = br_ref[:].reshape(b_tile2)
        bit = bi_ref[:].reshape(b_tile2)
        dr, di = _transpose_tile_dot(art, ait, brt, bit, ka, kb, precision)
        racc[:] += dr
        iacc[:] += di

        @pl.when(kidx == pl.num_programs(2) - 1)
        def _flush():
            re_ref[:] = racc[:]
            im_ref[:] = iacc[:]

    a_spec = pl.BlockSpec(a_block, a_map)
    b_spec = pl.BlockSpec(b_block, b_map)
    out_spec = pl.BlockSpec((tm, tn), lambda i, j, kk: (i, j))
    f32 = jnp.float32
    return pl.pallas_call(
        kernel,
        grid=(mm // tm, nn // tn, kk_total // tk),
        in_specs=[a_spec, a_spec, b_spec, b_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((mm, nn), f32),
            jax.ShapeDtypeStruct((mm, nn), f32),
        ],
        scratch_shapes=_scratch((tm, tn), f32),
        interpret=interpret,
    )(ar, ai, br, bi)


def fused_transpose_reference(
    ar, ai, br, bi,
    a_layout: OperandLayout,
    b_layout: OperandLayout,
    precision=None,
):
    """The fused transpose-dot as plain jax ops — no ``pallas_call``.

    Replays the kernel's exact grid: extracts the SAME stored-order
    blocks the ``BlockSpec`` index maps would fetch, squeezes them to
    the same 2-D tiles, runs the same shared per-tile body
    (:func:`_transpose_tile_dot`) and accumulates k tiles in the same
    ascending order — bit-identical by construction, so the interpret-
    mode parity tests prove the kernel moved streaming structure only.
    Python-looped over the grid: an oracle for tests and smokes, not an
    execution path.
    """
    import jax.numpy as jnp

    tiles = _plan_transpose_tiles(a_layout, b_layout)
    if tiles is None:
        raise ValueError("layouts not tileable")
    tm, tn, tk = tiles
    mm, nn = a_layout.f_size, b_layout.f_size
    kk_total = a_layout.k_size
    (a_block, a_frad, a_krad, ka, a_tile2), (
        b_block, b_frad, b_krad, kb, b_tile2,
    ) = _transpose_block_geometry(a_layout, b_layout, tm, tn, tk)

    def block(arr, lay, blk, frad, krad, fidx, kidx, tile2):
        coords = [0] * len(blk)
        _decompose(fidx, lay.f_axes, frad, coords)
        _decompose(kidx, lay.k_axes, krad, coords)
        sl = tuple(
            slice(c * b, (c + 1) * b) for c, b in zip(coords, blk)
        )
        return arr[sl].reshape(tile2)

    out_r = jnp.zeros((mm, nn), dtype=jnp.float32)
    out_i = jnp.zeros((mm, nn), dtype=jnp.float32)
    for i in range(mm // tm):
        for j in range(nn // tn):
            racc = jnp.zeros((tm, tn), dtype=jnp.float32)
            iacc = jnp.zeros((tm, tn), dtype=jnp.float32)
            for kidx in range(kk_total // tk):
                art = block(ar, a_layout, a_block, a_frad, a_krad, i, kidx, a_tile2)
                ait = block(ai, a_layout, a_block, a_frad, a_krad, i, kidx, a_tile2)
                brt = block(br, b_layout, b_block, b_frad, b_krad, j, kidx, b_tile2)
                bit = block(bi, b_layout, b_block, b_frad, b_krad, j, kidx, b_tile2)
                dr, di = _transpose_tile_dot(
                    art, ait, brt, bit, ka, kb, precision
                )
                racc = racc + dr
                iacc = iacc + di
            out_r = out_r.at[i * tm:(i + 1) * tm, j * tn:(j + 1) * tn].set(racc)
            out_i = out_i.at[i * tm:(i + 1) * tm, j * tn:(j + 1) * tn].set(iacc)
    return out_r, out_i


# -- fused multi-step residual chains -----------------------------------


class ChainLink:
    """Static metadata for one follow-on step of a fused chain: how the
    carried value (the previous step's output, a 2-D VMEM array) enters
    this step's dot against its pre-prepped ``(K, X)`` operand.

    ``carried_shape``: the 2-D matrix the flat carried value regroups
    to (a pure row-major reshape — :func:`tnc_tpu.ops.program.
    chain_groups` only admits steps whose carried operand needs no
    transpose). ``k_axis``: which axis of that matrix is the contract
    dim (0 = contract-first, 1 = contract-last). ``carried_first``:
    whether the carried value is the dot's first operand (its free axis
    supplies the output rows) — the PairStep ``swap`` folded out.
    """

    __slots__ = ("carried_first", "carried_shape", "k_axis")

    def __init__(
        self,
        carried_first: bool,
        carried_shape: tuple[int, int],
        k_axis: int,
    ):
        self.carried_first = bool(carried_first)
        self.carried_shape = (int(carried_shape[0]), int(carried_shape[1]))
        self.k_axis = int(k_axis)

    def out_shape(self, link_free: int) -> tuple[int, int]:
        free = self.carried_shape[1 - self.k_axis]
        if self.carried_first:
            return (free, link_free)
        return (link_free, free)


def chain_out_shape(
    m0: int, n0: int, links, link_frees
) -> tuple[int, int]:
    """Final 2-D output shape of a chain starting at ``(m0, n0)``."""
    shape = (m0, n0)
    for link, free in zip(links, link_frees):
        shape = link.out_shape(free)
    return shape


def _chain_compute(vals, links, precision):
    """The chain's arithmetic on plain arrays — shared verbatim by the
    Pallas kernel body (on VMEM-loaded values) and the bit-parity
    reference (:func:`fused_chain_reference`), so the only thing the
    kernel can add is dispatch fusion, never a numerical deviation."""
    import jax

    def cdot(xr, xi, yr, yi, xk, yk):
        dims = (((xk,), (yk,)), ((), ()))

        def dot(x, y):
            # accumulate in the operand dtype (f32 on the MXU path;
            # float64 split pairs — the complex128 CPU oracle — must
            # NOT downcast through the chain)
            return jax.lax.dot_general(
                x, y, dims,
                precision=precision,
                preferred_element_type=x.dtype,
            )

        return (
            dot(xr, yr) - dot(xi, yi),
            dot(xr, yi) + dot(xi, yr),
        )

    zr, zi = cdot(vals[0], vals[1], vals[2], vals[3], 0, 0)
    for i, link in enumerate(links):
        cr = vals[4 + 2 * i]
        ci = vals[5 + 2 * i]
        zr = zr.reshape(link.carried_shape)
        zi = zi.reshape(link.carried_shape)
        if link.carried_first:
            zr, zi = cdot(zr, zi, cr, ci, link.k_axis, 0)
        else:
            zr, zi = cdot(cr, ci, zr, zi, 0, link.k_axis)
    return zr, zi


def fused_chain_reference(first_ops, link_ops, links, precision=None):
    """The chain computation as plain jax ops — no ``pallas_call``.
    The bit-parity oracle for the interpret-mode tests: the kernel
    must produce the identical bits, proving fusion changed dispatch
    structure only."""
    vals = list(first_ops)
    for cr, ci in link_ops:
        vals.extend((cr, ci))
    return _chain_compute(vals, links, precision)


def fused_chain_kl(
    first_ops,
    link_ops,
    links,
    interpret: bool = False,
    precision=None,
):
    """Execute a whole residual chain as ONE Pallas dispatch.

    ``first_ops = (fr, fi, sr, si)``: the head step's two operands,
    pre-prepped to contract-dim-leading 2-D ``(K0, M0)`` / ``(K0, N0)``
    float32 arrays, already in dot order (``swap`` folded out by the
    caller). ``link_ops = [(cr, ci), ...]``: each follow-on step's
    non-carried operand, pre-prepped to ``(K_i, X_i)``. ``links``: one
    :class:`ChainLink` per follow-on step.

    Every array is a full-array VMEM block (no grid): the chain-grouping
    pass only admits runs whose combined operands and intermediates fit
    :data:`CHAIN_MAX_ELEMS`, so small residual steps stream through VMEM
    values with a single HBM round-trip at the chain boundary — the
    chain pays one dispatch overhead instead of ``len(links) + 1``.
    Arithmetic is the naive 4-real-dot complex lowering (same error
    contract as the single-step fused kernel).

    Returns the chain's final ``(re, im)`` 2-D float32 pair.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    fr, fi, sr, si = first_ops
    n_links = len(links)
    if n_links != len(link_ops):
        raise ValueError("links and link_ops must pair up")

    def kernel(*refs):
        ins, outs = refs[: 4 + 2 * n_links], refs[4 + 2 * n_links:]
        zr, zi = _chain_compute(
            [r[:] for r in ins], links, precision
        )
        outs[0][:] = zr
        outs[1][:] = zi

    out_shape = chain_out_shape(
        fr.shape[1], sr.shape[1], links, [c[0].shape[1] for c in link_ops]
    )
    flat_ins = [fr, fi, sr, si]
    for cr, ci in link_ops:
        flat_ins.extend((cr, ci))
    out_dtype = jnp.asarray(fr).dtype  # f32 device path; f64 oracle
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in flat_ins
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(out_shape, out_dtype),
            jax.ShapeDtypeStruct(out_shape, out_dtype),
        ],
        interpret=interpret,
    )(*flat_ins)
