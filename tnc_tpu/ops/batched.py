"""Batch-leg threading through compiled contraction programs.

Generic batched-execution machinery for the ops layer: given a
:class:`~tnc_tpu.ops.program.ContractionProgram` and a set of input
slots that carry a leading batch axis, :func:`thread_batch` marks, per
:class:`~tnc_tpu.ops.program.PairStep`, which operands carry the axis
(exactly the steps downstream of a batched slot), and
:func:`run_steps_batched` executes the program with each touched step
issued as ONE stacked matmul — the un-batched operand broadcasts, and
steps the axis never reaches run exactly once.

Per-batch-entry GEMMs see the same operands in the same summation
order as the singleton kernel (:func:`~tnc_tpu.ops.backends.
apply_step`'s host path), so on numpy a batch of B bit-compares to B
sequential executions — the contract `NumpyBackend.execute_batched`
and the serving layer (:mod:`tnc_tpu.serve.rebind`, the main consumer)
rely on.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

import numpy as np

from tnc_tpu.ops.backends import _prep_operand, apply_step
from tnc_tpu.ops.program import ContractionProgram


def thread_batch(
    program: ContractionProgram, batched_slots: Iterable[int]
) -> tuple[tuple[tuple[bool, bool], ...], bool]:
    """Propagate the batch leg through the program's steps.

    Returns ``(flags, feasible)``: ``flags[i] = (lhs_batched,
    rhs_batched)`` for step ``i``, and ``feasible`` is False when some
    step cannot carry the leg — its batched operand has a staged prep
    plan (``a_ops``/``b_ops``), whose reshape/lanemix shapes are baked
    for the flat buffer — in which case callers must use a
    vmap/stacked-dispatch fallback.

    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> from tnc_tpu.ops.program import build_program
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor.from_const([0], 2),
    ...                       LeafTensor.from_const([0], 2)])
    >>> program = build_program(tn, ContractionPath.simple([(0, 1)]))
    >>> thread_batch(program, [1])   # slot 1 carries the batch axis
    (((False, True),), True)
    """
    carried = set(batched_slots)
    flags: list[tuple[bool, bool]] = []
    feasible = True
    for st in program.steps:
        ab, bb = st.lhs in carried, st.rhs in carried
        if (ab and st.a_ops is not None) or (bb and st.b_ops is not None):
            feasible = False
        flags.append((ab, bb))
        if ab or bb:
            carried.add(st.lhs)
        else:
            carried.discard(st.lhs)
        carried.discard(st.rhs)
    return tuple(flags), feasible


def _prep_batched(xp, buf, view, perm, dot_shape):
    """Batched analogue of ``_prep_operand``: the leading batch axis
    rides through the fused-view reshape and macro transpose untouched."""
    b = buf.shape[0]
    v = buf.reshape((b,) + tuple(view))
    if perm is not None:
        v = xp.transpose(v, (0,) + tuple(p + 1 for p in perm))
    return v.reshape((b,) + tuple(dot_shape))


def _mat2(xp, v, mat, cfirst, batched):
    """Dot operand → ``(B?, k, f)`` matrix (k always the second-minor)."""
    if batched:
        b = v.shape[0]
        m = v.reshape((b,) + tuple(mat if cfirst else mat[::-1]))
        return m if cfirst else xp.swapaxes(m, -1, -2)
    m = v.reshape(tuple(mat if cfirst else mat[::-1]))
    return m if cfirst else m.T


def apply_step_batched(xp, a: Any, b: Any, step, ab: bool, bb: bool) -> Any:
    """One pairwise contraction with an optional leading batch axis on
    either operand. Reduces to the same 2-D GEMM per batch entry as the
    host path of :func:`~tnc_tpu.ops.backends.apply_step` (operands and
    summation order identical), so batched and sequential results
    bit-compare on the numpy oracle; on JAX, ``jnp.matmul`` lowers to
    one batched ``dot_general``."""
    if not (ab or bb):
        return apply_step(xp, a, b, step)
    av = (
        _prep_batched(xp, a, step.a_view, step.a_perm, step.a_dot)
        if ab
        else _prep_operand(xp, a, step.a_view, step.a_perm, step.a_dot, step.a_ops)
    )
    bv = (
        _prep_batched(xp, b, step.b_view, step.b_perm, step.b_dot)
        if bb
        else _prep_operand(xp, b, step.b_view, step.b_perm, step.b_dot, step.b_ops)
    )
    a2 = _mat2(xp, av, step.a_mat, step.a_cfirst, ab)  # (B?, k, m)
    b2 = _mat2(xp, bv, step.b_mat, step.b_cfirst, bb)  # (B?, k, n)
    if step.swap:
        out = xp.matmul(xp.swapaxes(b2, -1, -2) if bb else b2.T, a2)
    else:
        out = xp.matmul(xp.swapaxes(a2, -1, -2) if ab else a2.T, b2)
    batch = a.shape[0] if ab else b.shape[0]
    return out.reshape((batch,) + tuple(step.out_store))


def run_steps_batched(
    xp,
    program: ContractionProgram,
    buffers: list[Any],
    flags: Sequence[tuple[bool, bool]],
) -> Any:
    """Execute all steps with the batch leg threaded per ``flags``;
    result in ``(B,) + stored`` shape (the result is always batched when
    any batched slot feeds it)."""
    for st, (ab, bb) in zip(program.steps, flags):
        buffers[st.lhs] = apply_step_batched(
            xp, buffers[st.lhs], buffers[st.rhs], st, ab, bb
        )
        buffers[st.rhs] = None  # free eagerly
    return buffers[program.result_slot]


def stacked_rows(execute, buffers, batched_slots, b, result_shape):
    """Sequential stacked dispatch: run ``execute`` once per batch
    entry, selecting row ``i`` of each batched slot, and stack the
    results as ``(B,) + result_shape``. The ONE fallback loop shared by
    the numpy executor's non-threadable fallback and the serving
    layer's sliced and generic-backend paths."""
    bset = set(batched_slots)
    rows = [
        np.asarray(
            execute([x[i] if s in bset else x for s, x in enumerate(buffers)])
        )
        for i in range(b)
    ]
    return np.stack(rows).reshape((b,) + tuple(result_shape))
