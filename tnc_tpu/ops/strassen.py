"""Strassen-style contraction for the large slice-invariant stem GEMMs.

Tensor contraction is implicit matmul, and at the stem-GEMM shapes the
hoist pass isolates (``ops/hoist.py`` — big, square-ish, power-of-two
dims) a single Strassen recursion level gives a measurable speedup
(PAPERS.md, arXiv:1704.03092: one level ≈ 7/8 of the multiplies for a
few extra elementwise passes, profitable once the dims clear ~2^11).

Composition with split-complex arithmetic: a complex product lowers to
3 real GEMMs via the Gauss identity (``ops/split_complex.gauss_matmul``)
and each of those 3 runs one Strassen level — **3×7 = 21 half-size real
sub-GEMMs** against the naive lowering's 4 full GEMMs (= 32 half-size
multiply units): a 21/32 ≈ 0.66× multiply count. That factor is also
the *effective-flop credit* the benchmark applies so MFU numbers stay
comparable across kernel modes (``bench.py`` kernel buckets).

Layout convention matches the step compiler's dot layout and the fused
Pallas kernel: operands arrive contract-dim-leading, ``A: (K, M)``,
``B: (K, N)``, result ``AᵀB: (M, N)``. Written against that layout the
Strassen block sums are sums of contiguous ``(K/2, M/2)`` quadrants —
no operand is ever transposed; the transpose lives inside the
``dot_general`` contracting-dims spec (dim 0 × dim 0).

Numerics: Strassen's extra additions mix operand magnitudes before the
products, so rounding error grows a small constant factor over the
naive dot (same failure family as the Gauss/Karatsuba instability —
see ``split_complex.complex_mult_env``). The parity pins live in
``tests/test_strassen.py``; the documented tolerance rungs vs the
complex128 numpy oracle are **2e-5 relative (float32)** and **1e-12
relative (float64)** at one recursion level.
"""

from __future__ import annotations

#: one Strassen level only pays off once every matricized dim clears
#: this floor (calibrated crossover: below it the 15 extra elementwise
#: passes over quadrant-sized buffers cost more than the saved eighth
#: of the multiplies; 2^11 per dim ≈ the stem-GEMM regime).
STRASSEN_MIN_DIM = 1 << 11

#: "square-ish" guard: beyond this aspect ratio the problem is really a
#: panel GEMM — bandwidth-bound, where Strassen's extra passes hurt.
STRASSEN_MAX_ASPECT = 4.0

#: multiply-count credit of one gauss+strassen level vs the naive 4-dot
#: complex lowering: 3 Gauss products × 7 half-size sub-GEMMs = 21
#: half-units against naive's 4 GEMMs × 8 half-units = 32.
GAUSS_STRASSEN_FLOP_FACTOR = 21.0 / 32.0


def strassen_eligible(
    m: int,
    k: int,
    n: int,
    min_dim: int | None = None,
    max_aspect: float | None = None,
) -> bool:
    """Can one Strassen level run an ``(m, k) @ (k, n)`` problem
    profitably? Every dim must halve evenly (program dims are powers of
    two, so this only excludes degenerate odd shapes), clear the
    crossover floor, and the problem must be square-ish.

    >>> strassen_eligible(4096, 2048, 4096)
    True
    >>> strassen_eligible(4096, 1024, 4096)    # K below the crossover
    False
    >>> strassen_eligible(1 << 16, 2048, 2048)  # panel, not square-ish
    False
    >>> strassen_eligible(2049, 2048, 2048)     # odd dim cannot halve
    False
    """
    if min_dim is None:
        min_dim = STRASSEN_MIN_DIM
    if max_aspect is None:
        max_aspect = STRASSEN_MAX_ASPECT
    dims = (m, k, n)
    if any(d % 2 for d in dims):
        return False
    lo, hi = min(dims), max(dims)
    if lo < min_dim:
        return False
    return hi <= max_aspect * lo


def _kl_dot(xp, precision):
    """The base multiply for the (K, M)×(K, N) layout: contract dim 0
    of both operands. numpy has no dot_general; ``x.T @ y`` is the same
    contraction."""
    if xp.__name__.startswith("numpy"):
        return lambda x, y: x.T @ y
    from jax import lax

    def dot(x, y):
        return lax.dot_general(
            x, y, (((0,), (0,)), ((), ())), precision=precision
        )

    return dot


def strassen_dot_kl(xp, a, b, dot=None, precision=None):
    """One Strassen level of ``aᵀ @ b`` with ``a: (K, M)``, ``b: (K, N)``.

    Quadrants are taken in the *stored* kl layout — with ``X = aᵀ`` the
    logical Strassen operand, ``X[i][j] == a[j][i]ᵀ``, so every block
    sum is a sum of contiguous ``a`` quadrants and the only transposes
    are inside the 7 sub-products' contracting-dims spec. ``dot``
    overrides the sub-product kernel (the Pallas fused path could slot
    in here); default contracts dim 0 × dim 0 via matmul/dot_general.

    >>> import numpy as np
    >>> rng = np.random.default_rng(0)
    >>> a, b = rng.standard_normal((8, 6)), rng.standard_normal((8, 4))
    >>> np.allclose(strassen_dot_kl(np, a, b), a.T @ b)
    True
    """
    k, m = a.shape
    _, n = b.shape
    if k % 2 or m % 2 or n % 2:
        raise ValueError(f"shape (K={k}, M={m}, N={n}) does not halve")
    if dot is None:
        dot = _kl_dot(xp, precision)
    k2, m2, n2 = k // 2, m // 2, n // 2
    # a-quadrants in kl layout: X11 = a11ᵀ, X12 = a21ᵀ, X21 = a12ᵀ, ...
    a11, a21 = a[:k2, :m2], a[:k2, m2:]
    a12, a22 = a[k2:, :m2], a[k2:, m2:]
    b11, b12 = b[:k2, :n2], b[:k2, n2:]
    b21, b22 = b[k2:, :n2], b[k2:, n2:]
    # X11=a11ᵀ X12=a12ᵀ(from a[k2:, :m2]).. careful: X = aᵀ is (M, K);
    # X[row block i][col block j] = a[col block j][row block i]ᵀ:
    #   X11 = a[:k2, :m2]ᵀ   X12 = a[k2:, :m2]ᵀ
    #   X21 = a[:k2, m2:]ᵀ   X22 = a[k2:, m2:]ᵀ
    x11, x12 = a11, a12
    x21, x22 = a21, a22
    p1 = dot(x11 + x22, b11 + b22)  # (X11+X22)(Y11+Y22)
    p2 = dot(x21 + x22, b11)        # (X21+X22)Y11
    p3 = dot(x11, b12 - b22)        # X11(Y12-Y22)
    p4 = dot(x22, b21 - b11)        # X22(Y21-Y11)
    p5 = dot(x11 + x12, b22)        # (X11+X12)Y22
    p6 = dot(x21 - x11, b11 + b12)  # (X21-X11)(Y11+Y12)
    p7 = dot(x12 - x22, b21 + b22)  # (X12-X22)(Y21+Y22)
    c11 = p1 + p4 - p5 + p7
    c12 = p3 + p5
    c21 = p2 + p4
    c22 = p1 - p2 + p3 + p6
    top = xp.concatenate([c11, c12], axis=1)
    bot = xp.concatenate([c21, c22], axis=1)
    return xp.concatenate([top, bot], axis=0)


def gauss_strassen_dot_kl(xp, ar, ai, br, bi, precision=None):
    """``(re, im)`` of ``(ar + i·ai)ᵀ @ (br + i·bi)`` via the Gauss
    3-mult complex identity with one Strassen level per real product:
    3×7 = 21 half-size real sub-GEMMs against the naive lowering's 4
    full dots. Same kl layout as :func:`strassen_dot_kl`.

    >>> import numpy as np
    >>> rng = np.random.default_rng(1)
    >>> ar, ai = rng.standard_normal((8, 6)), rng.standard_normal((8, 6))
    >>> br, bi = rng.standard_normal((8, 4)), rng.standard_normal((8, 4))
    >>> re, im = gauss_strassen_dot_kl(np, ar, ai, br, bi)
    >>> want = (ar + 1j * ai).T @ (br + 1j * bi)
    >>> np.allclose(re + 1j * im, want)
    True
    """
    dot = _kl_dot(xp, precision)
    k1 = strassen_dot_kl(xp, ar + ai, br, dot=dot)
    k2 = strassen_dot_kl(xp, ar, bi - br, dot=dot)
    k3 = strassen_dot_kl(xp, ai, br + bi, dot=dot)
    return k1 - k3, k1 + k2
