"""Slice-invariant stem hoisting for sliced contraction programs.

A :class:`~tnc_tpu.ops.sliced.SlicedProgram` re-runs its whole step list
once per slice-index combination, yet every step whose operands contain
no sliced leg (transitively — a value computed *from* a sliced leaf is
per-slice even after the sliced leg itself is contracted away) produces
bit-identical output in all ``num_slices`` iterations. This module
splits the program into:

- an **invariant prelude** — the steps reachable only from unsliced
  inputs, executed exactly once; and
- a **per-slice residual** — a standard :class:`SlicedProgram` whose
  extra input slots are the prelude's cached intermediates, so every
  existing sliced executor (numpy oracle, on-device loop, chunked,
  SPMD) runs it unchanged.

The marking pass is linear in the step count. Replace-path semantics
guarantee each intermediate value is consumed by exactly one step, so
the prelude/residual interface is a flat list of cached buffers — no
value is both consumed inside the prelude and re-read by the residual
from a stale slot.

Cost model: naive sliced execution costs ``num_slices * total_flops``;
hoisted execution costs ``invariant_flops + num_slices *
residual_flops``. The slicing planner scores candidate slice sets with
the hoisted formula (:mod:`tnc_tpu.contractionpath.slicing`), so leg
selection actively prefers slicings that keep a large hoistable stem.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import lru_cache
from typing import Any, Sequence

from tnc_tpu.ops.program import ContractionProgram, PairStep, steps_flops
from tnc_tpu.ops.sliced import SlicedProgram


@dataclass(frozen=True)
class PreludeStep:
    """One invariant contraction, remapped into the prelude slot space.

    ``step`` carries the shape metadata only — its baked-in ``lhs``/
    ``rhs`` slot ids refer to the *original* program and must not be
    used; ``out``/``lhs``/``rhs`` here are prelude slots. ``free_rhs``
    is False when the rhs value is a residual source and must survive
    the step (never the case for tree paths, kept for safety)."""

    out: int
    lhs: int
    rhs: int
    free_rhs: bool
    step: PairStep


@dataclass(frozen=True)
class HoistedProgram:
    """A sliced program split into (once-only prelude, per-slice residual).

    ``residual`` is a self-contained :class:`SlicedProgram` over a fresh
    input slot space; ``residual_sources[slot]`` says where each residual
    input comes from: ``("leaf", original_input_slot)`` for inputs the
    variant steps read directly (sliced leaves keep their slice-indexing
    info, unsliced leaves pass through), or ``("cached", prelude_slot)``
    for prelude intermediates. When the hoist degrades to a no-op
    (``prelude_steps == ()``), ``residual`` is the original program and
    every source is a pass-through leaf."""

    residual: SlicedProgram
    prelude_steps: tuple[PreludeStep, ...]
    prelude_num_slots: int
    # (prelude_slot, original_input_slot) for each prelude input
    prelude_inputs: tuple[tuple[int, int], ...]
    residual_sources: tuple[tuple[str, int], ...]

    @property
    def is_noop(self) -> bool:
        return not self.prelude_steps

    def signature(self) -> tuple:
        return (
            self.residual.signature(),
            self.prelude_steps,
            self.prelude_num_slots,
            self.prelude_inputs,
            self.residual_sources,
        )


@lru_cache(maxsize=128)
def hoist_sliced_program(sp: SlicedProgram) -> HoistedProgram:
    """Split ``sp`` into an invariant prelude and a per-slice residual.

    Degrades to a no-op (empty prelude, residual ``is`` the original
    program) when every step depends on a sliced leg, when no step does
    (``num_slices == 1`` programs), or when the program has no steps.

    >>> import numpy as np
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> from tnc_tpu.contractionpath.slicing import Slicing
    >>> from tnc_tpu.ops.sliced import build_sliced_program
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> rng = np.random.default_rng(0)
    >>> mk = lambda legs: LeafTensor(
    ...     legs, [4] * len(legs),
    ...     TensorData.matrix(rng.standard_normal([4] * len(legs))))
    >>> ring = CompositeTensor([mk([0, 1]), mk([1, 2]), mk([2, 3]),
    ...                         mk([3, 0])])
    >>> path = ContractionPath.simple([(0, 3), (0, 1), (0, 2)])
    >>> sp = build_sliced_program(ring, path, Slicing((2,), (4,)))
    >>> hp = hoist_sliced_program(sp)  # (0, 3) touches no sliced leg
    >>> len(hp.prelude_steps), len(hp.residual.program.steps)
    (1, 2)
    """
    prog = sp.program
    steps = prog.steps
    n = prog.num_inputs

    # --- marking pass: value-level variant propagation ------------------
    # value ids: ("leaf", slot) | ("step", index)
    variant: dict[tuple, bool] = {
        ("leaf", s): bool(sp.slot_slices[s]) for s in range(n)
    }
    cur: dict[int, tuple] = {s: ("leaf", s) for s in range(n)}
    operands: list[tuple[tuple, tuple]] = []
    step_variant: list[bool] = []
    for i, st in enumerate(steps):
        va, vb = cur[st.lhs], cur[st.rhs]
        is_var = variant[va] or variant[vb]
        operands.append((va, vb))
        step_variant.append(is_var)
        out = ("step", i)
        variant[out] = is_var
        cur[st.lhs] = out
        cur[st.rhs] = ("dead", i)

    if not steps or all(step_variant) or not any(step_variant):
        return HoistedProgram(
            residual=sp,
            prelude_steps=(),
            prelude_num_slots=0,
            prelude_inputs=(),
            residual_sources=tuple(("leaf", s) for s in range(n)),
        )

    # --- residual: variant steps remapped onto a fresh slot space -------
    res_slot_of: dict[tuple, int] = {}
    res_sources: list[tuple[str, Any]] = []
    res_slot_slices: list[tuple] = []
    res_steps: list[PairStep] = []

    def res_input(v: tuple) -> int:
        slot = len(res_sources)
        res_slot_of[v] = slot
        if v[0] == "leaf":
            res_sources.append(("leaf", v[1]))
            res_slot_slices.append(sp.slot_slices[v[1]])
        else:  # invariant intermediate: cached by the prelude
            res_sources.append(("cached", v))
            res_slot_slices.append(())
        return slot

    for i, st in enumerate(steps):
        if not step_variant[i]:
            continue
        va, vb = operands[i]
        la = res_slot_of.get(va)
        if la is None:
            la = res_input(va)
        lb = res_slot_of.get(vb)
        if lb is None:
            lb = res_input(vb)
        res_steps.append(replace(st, lhs=la, rhs=lb))
        res_slot_of[("step", i)] = la

    final_val = cur[prog.result_slot]
    assert variant[final_val], "variant steps exist, so the result is variant"
    residual_program = ContractionProgram(
        num_inputs=len(res_sources),
        steps=tuple(res_steps),
        result_slot=res_slot_of[final_val],
        result_legs=prog.result_legs,
        result_shape=prog.result_shape,
        stored_result_shape=prog.stored_result_shape,
        canonical_legs=prog.canonical_legs,
    )
    residual = SlicedProgram(
        residual_program, sp.slicing, tuple(res_slot_slices)
    )

    # --- prelude: invariant steps, replace-left over a compact space ----
    needed = {v for kind, v in res_sources if kind == "cached"}
    pslot: dict[tuple, int] = {}
    prelude_inputs: list[tuple[int, int]] = []
    prelude_steps: list[PreludeStep] = []
    nslots = 0

    def palloc() -> int:
        nonlocal nslots
        nslots += 1
        return nslots - 1

    for i, st in enumerate(steps):
        if step_variant[i]:
            continue
        va, vb = operands[i]
        for v in (va, vb):
            if v not in pslot:
                # every non-step operand of an invariant step is a leaf
                assert v[0] == "leaf", v
                s = palloc()
                pslot[v] = s
                prelude_inputs.append((s, v[1]))
        la, lb = pslot[va], pslot[vb]
        # replace-left reuses la unless the consumed value must survive
        # for the residual (impossible on tree paths — defensive only)
        out_slot = palloc() if va in needed else la
        prelude_steps.append(
            PreludeStep(out_slot, la, lb, vb not in needed, st)
        )
        pslot[("step", i)] = out_slot

    patched_sources = tuple(
        (kind, pslot[ref] if kind == "cached" else ref)
        for kind, ref in res_sources
    )
    return HoistedProgram(
        residual=residual,
        prelude_steps=tuple(prelude_steps),
        prelude_num_slots=nslots,
        prelude_inputs=tuple(prelude_inputs),
        residual_sources=patched_sources,
    )


def run_prelude_steps(
    xp,
    hp: HoistedProgram,
    prelude_buffers: Sequence[Any],
    split_complex: bool = False,
    precision=None,
) -> list[Any]:
    """Execute the prelude steps over ``prelude_buffers`` (one buffer
    per ``hp.prelude_inputs`` entry, in that order; (real, imag) pairs
    in split mode) and return the cached intermediates in the order the
    ``("cached", …)`` entries appear in ``hp.residual_sources``. Works
    under tracing (``xp = jnp`` inside a jit) and on the host oracle
    (``xp = np``) alike.

    Split-mode prelude steps ride the kernel promotion ladder: the
    slice-invariant stem GEMMs this pass isolates are exactly the big,
    square-ish shapes one Strassen level pays off on, so each step over
    the crossover runs gauss+strassen
    (:func:`tnc_tpu.ops.split_complex.auto_step_mode`) unless a
    ``TNC_TPU_COMPLEX_MULT`` forcing override pins the mode — which is
    why the executors key their compiled-fn caches on
    :func:`tnc_tpu.ops.split_complex.complex_mult_key`, not the env
    default. The dot-precision rung behaves the same way: a
    ``TNC_TPU_DOT_PRECISION`` forcing override reaches every prelude
    dot through ``apply_step_split``'s per-step resolve (the caches
    key on :func:`tnc_tpu.ops.split_complex.dot_precision_key`); the
    model-driven per-step promotion deliberately does NOT — like
    :func:`~tnc_tpu.ops.split_complex.auto_step_mode`, an env-keyed
    trace must never bake in a decision that flaps as calibration
    evolves."""
    if split_complex:
        from tnc_tpu.ops.split_complex import apply_step_split, auto_step_mode

        def kernel(a, b, step):
            return apply_step_split(
                xp, a, b, step, precision, mode=auto_step_mode(step)
            )

    else:
        from tnc_tpu.ops.backends import apply_step

        def kernel(a, b, step):
            return apply_step(xp, a, b, step)

    buf: list[Any] = [None] * hp.prelude_num_slots
    for (slot, _), val in zip(hp.prelude_inputs, prelude_buffers):
        buf[slot] = val
    for ps in hp.prelude_steps:
        out = kernel(buf[ps.lhs], buf[ps.rhs], ps.step)
        if ps.free_rhs:
            buf[ps.rhs] = None
        buf[ps.out] = out
    return [
        buf[ref] for kind, ref in hp.residual_sources if kind == "cached"
    ]


def run_prelude(
    xp,
    hp: HoistedProgram,
    arrays: Sequence[Any],
    split_complex: bool = False,
    precision=None,
) -> list[Any]:
    """Execute the prelude once and assemble the residual input buffers.

    ``arrays`` are the *original* program's full input buffers ((real,
    imag) pairs in split mode). Returns one buffer per residual input
    slot: pass-through leaves by reference, cached prelude intermediates
    freshly computed."""
    if hp.is_noop:
        return list(arrays)
    cached = iter(
        run_prelude_steps(
            xp,
            hp,
            [arrays[orig] for _, orig in hp.prelude_inputs],
            split_complex,
            precision,
        )
    )
    return [
        arrays[ref] if kind == "leaf" else next(cached)
        for kind, ref in hp.residual_sources
    ]


def hoist_split_counts(sp: SlicedProgram) -> dict:
    """JSON-able summary of the hoist split — how many steps run once
    (prelude) vs per slice (residual), and the flops on each side.
    Persisted next to path + slicing by the serving plan cache so a
    cached plan records the stem it was scored with."""
    hp = hoist_sliced_program(sp)
    return {
        "prelude_steps": len(hp.prelude_steps),
        "residual_steps": len(hp.residual.program.steps),
        "invariant_flops": float(
            steps_flops(ps.step for ps in hp.prelude_steps)
        ),
        "residual_flops": float(steps_flops(hp.residual.program.steps)),
    }


def hoist_step_flops(sp: SlicedProgram) -> tuple[float, float]:
    """(invariant_flops, per-slice residual_flops) of the compiled
    program, from the steps' dot shapes (naive multiply-add count per
    step: ``k * m * n``). Hoisted total cost is ``invariant + num_slices
    * residual``; the naive executor pays ``num_slices * (invariant +
    residual)``."""
    hp = hoist_sliced_program(sp)
    return (
        steps_flops(ps.step for ps in hp.prelude_steps),
        steps_flops(hp.residual.program.steps),
    )
