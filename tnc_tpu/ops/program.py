"""Contraction-path → static execution program compiler.

The reference executes a path as a loop of TBLIS einsum calls, one per pair
(``tnc/src/tensornetwork/contraction.rs:52-57,88-116``). On TPU, the whole
path is known before execution and every shape is static, so we compile it
once into a :class:`ContractionProgram`: a flat list of :class:`PairStep`
dot-contractions traced into one (or a few) XLA programs.

TPU layout discipline (the design constraint that shapes this module):
an f32 array is stored in (sublane×128-lane) tiles over its two trailing
dims, and a trailing dim < 128 is *padded up to 128* — a high-rank
quantum-circuit tensor stored as (…, 2, 2) wastes up to 64× HBM and
bandwidth. The compiler therefore guarantees:

- **Stored form**: every intermediate lives in its dot-output shape with
  trailing axes merged until the minor dim is ≥ 128 (`_storage_merge`) —
  zero tile padding for every large buffer.
- **One aligned macro-transpose per operand, or none**: an operand is
  brought to ``(contracted…, free…)`` order by a single low-rank
  transpose over *run-fused* macro axes. Intra-group leg order always
  follows the operand's stored order (never a leg-id sort), so the
  permutation degrades into a handful of contiguous block moves whose
  output keeps a large minor dim.
- **dot_general with contiguous contracting dims**: the contraction
  itself never asks XLA to relayout an operand internally.
- **Consumer alignment**: each step knows which of its output legs the
  next step contracts (`next_shared`) and emits its free legs as
  [consumer-contracted…, consumer-kept…] (stored-order within each), so
  the consumer's transpose is a ≤4-block permutation. Storage merges
  also stop at that boundary, keeping the consumer's reshape view a
  layout-free regroup.

The whole-program jit then keeps intermediates in HBM, fuses elementwise
glue, and frees buffers eagerly (the reference frees inputs per step via
``Option::take``, ``contraction.rs:39,53-56``; XLA liveness does the same
here).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor, Tensor

_MIN_MINOR = 128  # f32 lane tile: trailing dims below this pad up to it
_STAGED_MIN_SIZE = 1 << 18  # staged prep only pays off for big operands
_STAGED_PAD_FACTOR = 4.0  # naive materialization tolerated up to this
# widest lane window the staged planner accepts (bounds the host-side
# index table; execution uses a gather above the matmul cap)
_LANEMIX_MAX_W = 65536


def step_dims(st) -> tuple[int, int, int]:
    """The ``(m, k, n)`` matmul shape of one :class:`PairStep`: the dot
    contracts a ``(m, k)`` lhs against a ``(k, n)`` rhs (orientation and
    ``swap`` folded out — these are the *logical* dims every cost shares).
    """
    k = st.a_dot[0] if st.a_cfirst else st.a_dot[-1]
    m = math.prod(st.a_dot) // max(k, 1)
    n = math.prod(st.b_dot) // max(k, 1)
    return int(m), int(k), int(n)


def step_flops(st) -> float:
    """Naive multiply-add count of one step: ``k * m * n``."""
    m, k, n = step_dims(st)
    return float(k) * float(m) * float(n)


def step_prep_elems(st) -> float:
    """Elements the step's operand *prep* moves through HBM on top of
    the dot itself: a materialized macro transpose (or staged op plan)
    reads the whole operand and writes the permuted copy — ``2 ×
    view`` elements per permuted operand. Zero for identity preps
    (reshape-only — layout-free on TPU). This is the pass the
    ``fused_transpose`` kernel rung deletes
    (:mod:`tnc_tpu.ops.pallas_complex`), and the traffic the original
    ``steps_bytes`` under-predicted on transpose-dominated steps (the
    r04 roofline misprediction).

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> tn = CompositeTensor([LeafTensor.from_const([0, 1], 4),
    ...                       LeafTensor.from_const([1, 2], 4)])
    >>> program = build_program(tn, ContractionPath.simple([(0, 1)]))
    >>> step_prep_elems(program.steps[0])   # identity preps: no pass
    0.0
    """
    extra = 0.0
    for view, perm, ops in (
        (st.a_view, st.a_perm, st.a_ops),
        (st.b_view, st.b_perm, st.b_ops),
    ):
        if perm is not None or ops:
            extra += 2.0 * float(math.prod(view))
    return extra


def step_elems(st, mode: str | None = None) -> tuple[float, float]:
    """(elements read+moved, elements written) by one step — the
    operands' stored views in plus the prep pass
    (:func:`step_prep_elems`: a materialized macro transpose reads and
    writes the operand again before the dot sees it), the stored
    result out. Multiplied by the dtype width this is the step's
    predicted HBM traffic, the bytes side of the roofline next to
    :func:`step_flops`.

    ``mode`` is the kernel-ladder mode that will run the step:
    ``fused_transpose`` streams the permutation inside the kernel's
    index maps, so its prediction drops the prep pass — the saved
    traffic the spans and the roofline must credit."""
    elems_in = float(math.prod(st.a_view)) + float(math.prod(st.b_view))
    if mode != "fused_transpose":
        elems_in += step_prep_elems(st)
    return elems_in, float(math.prod(st.out_store))


def step_label(i: int, st) -> str:
    """Self-describing span name for one step: index + matmul dims
    (``step[12] 256x512·512x64``), so Perfetto lanes and roofline rows
    read without cross-referencing the program dump.

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> tn = CompositeTensor([LeafTensor.from_const([0, 1], 4),
    ...                       LeafTensor.from_const([1, 2], 4)])
    >>> program = build_program(tn, ContractionPath.simple([(0, 1)]))
    >>> step_label(0, program.steps[0])
    'step[0] 4x4·4x4'
    """
    m, k, n = step_dims(st)
    return f"step[{i}] {m}x{k}·{k}x{n}"


def steps_flops(steps) -> float:
    """Naive multiply-add count of a step sequence (``k * m * n`` per
    dot) — the shared formula under the hoist accounting
    (:func:`tnc_tpu.ops.hoist.hoist_step_flops`) and the obs span flop
    counters, so measured and predicted costs are comparable.

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> tn = CompositeTensor([LeafTensor.from_const([0, 1], 4),
    ...                       LeafTensor.from_const([1, 2], 4)])
    >>> program = build_program(tn, ContractionPath.simple([(0, 1)]))
    >>> steps_flops(program.steps)   # one (4,4) @ (4,4) dot
    64.0
    """
    return sum(step_flops(st) for st in steps)


def steps_bytes(steps, dtype_bytes: float = 16.0) -> float:
    """Predicted HBM traffic of a step sequence: per step, operands
    read + the prep pass (a materialized macro transpose moves the
    operand through HBM again — read + write; :func:`step_prep_elems`)
    + result written, times the element width (complex128 = 16 by
    default; the executors pass their actual width). The bytes
    counterpart of :func:`steps_flops` on the obs spans, so the
    calibration fit (:mod:`tnc_tpu.obs.calibrate`) sees both roofline
    axes — including the transpose traffic it used to be blind to on
    transpose-dominated steps.

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> tn = CompositeTensor([LeafTensor.from_const([0, 1], 4),
    ...                       LeafTensor.from_const([1, 2], 4)])
    >>> program = build_program(tn, ContractionPath.simple([(0, 1)]))
    >>> steps_bytes(program.steps, 1.0)   # 16 + 16 read, 16 written
    48.0
    """
    total = 0.0
    for st in steps:
        elems_in, elems_out = step_elems(st)
        total += (elems_in + elems_out) * dtype_bytes
    return total


def chain_groups(
    steps,
    max_flops: float | None = None,
    max_elems: float | None = None,
) -> tuple[tuple[int, int], ...]:
    """Runs of consecutive steps executable as ONE fused Pallas chain
    dispatch (:func:`tnc_tpu.ops.pallas_complex.fused_chain_kl`).

    A step extends the running chain when it consumes the chain's
    current value (its ``lhs`` or ``rhs`` is the chain's result slot —
    replace-left semantics guarantee that slot still holds the chained
    value), the carried operand's prep is a pure row-major regroup
    (no macro transpose, no staged ops — the value must flow through
    VMEM as a reshape), and the whole run stays small: every step
    strictly under the ``max_flops`` ceiling in the fused kernel's
    ``2*k*m*n`` units (default ``MIN_FLOPS`` — exactly the
    dispatch-dominated steps the single-step kernel rejects and the
    ``small`` shape bucket of :func:`tnc_tpu.ops.split_complex.
    step_bucket`; :func:`tnc_tpu.ops.split_complex.plan_kernel_steps`
    raises the ceiling with the calibrated ``dispatch_overhead_s``, so
    chained steps can also come from the ``medium`` bucket when the
    fitted model says they're still dispatch-bound) with all operands
    + intermediates summing under ``max_elems`` float32 elements
    ((real, imag) pairs count double).

    Returns ``(start, end)`` index spans, each covering ≥ 2 steps;
    steps outside every span dispatch individually.

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> tn = CompositeTensor([LeafTensor.from_const([0, 1], 4),
    ...                       LeafTensor.from_const([1, 2], 4),
    ...                       LeafTensor.from_const([2, 3], 4)])
    >>> program = build_program(tn, ContractionPath.simple([(0, 1), (0, 2)]))
    >>> chain_groups(program.steps)
    ((0, 2),)
    """
    if max_flops is None:
        from tnc_tpu.ops.pallas_complex import MIN_FLOPS

        max_flops = float(MIN_FLOPS)
    if max_elems is None:
        from tnc_tpu.ops.pallas_complex import CHAIN_MAX_ELEMS

        max_elems = float(CHAIN_MAX_ELEMS)

    def step_cost_elems(st) -> float:
        # VMEM *residency* of the step's operands and result — NOT
        # step_elems, whose total includes the HBM prep-pass traffic
        # (step_prep_elems): counting that here would shrink chain
        # admission for transpose-feeding steps for no footprint reason
        elems_in = float(math.prod(st.a_view)) + float(math.prod(st.b_view))
        elems_out = float(math.prod(st.out_store))
        return 2.0 * (elems_in + elems_out)  # (real, imag) pairs

    def small(st) -> bool:
        # same 2*k*m*n units and strict bound as pallas eligibility
        # and step_bucket's "small" — the three must agree
        return 2.0 * step_flops(st) < max_flops

    groups: list[tuple[int, int]] = []
    start: int | None = None
    run_slot = -1
    run_elems = 0.0

    def close(end: int) -> None:
        nonlocal start
        if start is not None and end - start >= 2:
            groups.append((start, end))
        start = None

    for i, st in enumerate(steps):
        cost = step_cost_elems(st)
        if start is not None:
            carried_a = st.lhs == run_slot
            carried_b = st.rhs == run_slot
            trivial = (
                (st.a_perm is None and st.a_ops is None)
                if carried_a
                else (st.b_perm is None and st.b_ops is None)
            )
            if (
                (carried_a or carried_b)
                and trivial
                and small(st)
                and run_elems + cost <= max_elems
            ):
                run_slot = st.lhs
                run_elems += cost
                continue
            close(i)
        if small(st) and cost <= max_elems:
            start = i
            run_slot = st.lhs
            run_elems = cost
        else:
            start = None
    close(len(steps))
    return tuple(groups)


def _padded_elems(shape) -> float:
    """Tile-padded element count; single source of truth in
    :func:`tnc_tpu.ops.budget.padded_elems` (minor dim pads to 128; XLA
    shrinks sublane tiles for small second-minor dims, so those don't)."""
    from tnc_tpu.ops.budget import padded_elems

    return float(padded_elems(tuple(shape)))


def _naive_prep_bad(view, perm) -> bool:
    """True when executing ``reshape(view); transpose(perm)`` would
    materialize a buffer padded more than ``_STAGED_PAD_FACTOR``× its
    logical size (the BENCH_r02/r03 OOM mode: high-rank views with tiny
    trailing dims tile-pad 16-128×)."""
    if perm is None:
        return False
    size = math.prod(view)
    if size < _STAGED_MIN_SIZE:
        return False
    out_view = [view[p] for p in perm]
    worst = max(_padded_elems(view), _padded_elems(out_view))
    return worst > _STAGED_PAD_FACTOR * size


def _fused_transpose(src, dst, dims, tail):
    """Run-fused (view, axes) for a transpose of row legs ``src`` →
    ``dst`` above an intact fused ``tail`` dim. Legs adjacent in both
    orders collapse into one axis, keeping the materialized rank low
    (sublane padding shrinks with fewer, larger dims)."""
    pos = {l: i for i, l in enumerate(dst)}
    runs: list[list[int]] = []
    for l in src:
        if runs and pos[l] == pos[runs[-1][-1]] + 1:
            runs[-1].append(l)
        else:
            runs.append([l])
    view = tuple(int(math.prod(dims[l] for l in r)) for r in runs) + (tail,)
    order = sorted(range(len(runs)), key=lambda i: pos[runs[i][0]])
    axes = tuple(order) + (len(runs),)
    return view, axes


def _staged_ops(
    dims: list[int], perm: list[int], min_minor: int = _MIN_MINOR
) -> tuple | None:
    """Decompose an axis permutation into materialization-safe device ops.

    ``dims``: stored axis dims (leg granularity); ``perm``: target order.
    Returns a tuple of primitive ops — ``("reshape", shape)``,
    ``("transpose", axes)``, ``("lanemix", W, idx)`` — whose execution
    turns a flat buffer in ``dims`` order into ``perm`` order while every
    materialized intermediate keeps a minor dim ≥ ``min_minor`` (so XLA's
    (8, 128) tiling never lane-pads it). ``None`` ⇒ not plannable (use
    the naive reshape/transpose).

    Construction: legs that stay out of the trailing ≥128-element window
    move with cheap leading-dim transposes (the fused tail rides along
    untouched); legs crossing into or out of that window are repositioned
    by ONE static permutation of the lane window (``lanemix``) — executed
    as an exact one-hot matmul on the MXU or a gather, never as a padded
    high-rank relayout.
    """
    n = len(dims)
    total = int(math.prod(dims))
    if tuple(perm) == tuple(range(n)):
        return ()
    if total < min_minor * 2:
        return None

    # minimal target suffix with prod >= min_minor: the final fused tail
    tprod, t_i = 1, n
    while t_i > 0 and tprod < min_minor:
        t_i -= 1
        tprod *= dims[perm[t_i]]
    tset = set(perm[t_i:])
    rows_final = list(perm[:t_i])

    # minimal stored suffix with prod >= min_minor: the base lane window
    bprod, b_i = 1, n
    while b_i > 0 and bprod < min_minor:
        b_i -= 1
        bprod *= dims[b_i]
    bset = set(range(b_i, n))

    rows_stored = list(range(b_i))
    cross_in = [l for l in rows_stored if l in tset]  # must enter the tail
    cross_out = [l for l in rows_final if l in bset]  # must leave the tail
    W = int(math.prod(dims[l] for l in cross_in)) * bprod

    ops: list[tuple] = []
    nonwin_rows = [l for l in rows_final if l not in bset and l not in tset]

    # phase A: leading transpose bringing tail-bound legs next to the
    # window; the fused base tail (>=128) rides along as the minor dim
    rows_a = nonwin_rows + cross_in
    if rows_a != rows_stored:
        view, axes = _fused_transpose(rows_stored, rows_a, dims, bprod)
        ops.append(("reshape", view))
        if axes != tuple(range(len(view))):
            ops.append(("transpose", axes))

    # phase B: one static lane permutation over the window
    window_cur = cross_in + list(range(b_i, n))
    window_new = cross_out + list(perm[t_i:])
    if window_new != window_cur:

        def lane_table(cur, new):
            """Index table mapping new mixed-radix positions to old."""
            pos_cur = {l: i for i, l in enumerate(cur)}
            strides = [1] * len(cur)
            for i in range(len(cur) - 2, -1, -1):
                strides[i] = strides[i + 1] * dims[cur[i + 1]]
            new_strides = [1] * len(new)
            for i in range(len(new) - 2, -1, -1):
                new_strides[i] = new_strides[i + 1] * dims[new[i + 1]]
            width = int(math.prod(dims[l] for l in new))
            table = []
            for j in range(width):
                old = 0
                for l, s in zip(new, new_strides):
                    old += ((j // s) % dims[l]) * strides[pos_cur[l]]
                table.append(old)
            return table

        # NOTE a fixed ≥128 trailing block can't be factored out here:
        # both windows are *minimal* ≥128 suffixes, so a shared trailing
        # block that large would make them identical and phase B would
        # have been skipped (review r3) — the full-width table is the
        # only shape the permutation takes. Wide windows execute as a
        # gather (see ``_lanemix_jax``), so only the host-side table
        # size bounds W.
        if W > _LANEMIX_MAX_W:
            return None
        ops.append(("reshape", (total // W, W)))
        ops.append(("lanemix", W, tuple(lane_table(window_cur, window_new))))

    # phase C: split the window's outbound legs and finish the row order
    rows_b = nonwin_rows + cross_out
    view, axes = _fused_transpose(rows_b, rows_final, dims, tprod)
    ops.append(("reshape", view))
    if axes != tuple(range(len(view))):
        ops.append(("transpose", axes))
    return tuple(ops)


@dataclass(frozen=True)
class PairStep:
    """One pairwise contraction, fully shape-resolved.

    Executors reshape each operand's stored buffer to the run-fused
    ``*_view``, apply ``*_perm`` (identity ⇒ ``None``), contract the
    leading ``n_contract`` axes of both views against each other
    (``lax.dot_general`` on device, 2-D matmul on the host oracle), and
    store the result reshaped to ``out_store``.

    ``swap``: the dot is issued as (rhs, lhs) so the operand with the
    larger trailing free run supplies the output's minor dims.
    """

    lhs: int  # slot of left input (result replaces this slot)
    rhs: int  # slot of right input (freed after the step)
    a_view: tuple[int, ...]  # fused macro view of lhs stored buffer
    a_perm: tuple[int, ...] | None  # macro transpose (contract/free grouped)
    a_dot: tuple[int, ...]  # post-perm reshape: (k, frees…) or (frees…, k)
    a_cfirst: bool  # True: k is a_dot[0]; False: k is a_dot[-1]
    b_view: tuple[int, ...]
    b_perm: tuple[int, ...] | None
    b_dot: tuple[int, ...]
    b_cfirst: bool
    swap: bool  # issue dot as (b, a): output legs = b_free ++ a_free
    out_store: tuple[int, ...]  # storage shape of the result buffer
    # staged device prep (see `_staged_ops`): when set, device executors
    # run these ops instead of the naive reshape/transpose, keeping every
    # materialized buffer's minor dim >= 128 (no lane tile padding). The
    # host oracle still uses the equivalent (view, perm) pair.
    a_ops: tuple | None = None
    b_ops: tuple | None = None

    @property
    def a_mat(self) -> tuple[int, int]:
        """2-D (k, m) view for the host matmul oracle (orientation folded
        out by ``apply_step``)."""
        k = self.a_dot[0] if self.a_cfirst else self.a_dot[-1]
        return (k, int(math.prod(self.a_dot)) // max(k, 1))

    @property
    def b_mat(self) -> tuple[int, int]:
        k = self.b_dot[0] if self.b_cfirst else self.b_dot[-1]
        return (k, int(math.prod(self.b_dot)) // max(k, 1))


def _storage_merge(
    dims: list[int], categories: list[int] | None = None
) -> tuple[int, ...]:
    """Merge adjacent axes into a storage shape: all same-category runs
    collapse, and trailing axes keep merging (across categories if
    necessary) until the minor dim reaches ``_MIN_MINOR``.

    ``categories[i]`` groups axes the *consumer* treats alike (contracted
    vs kept); merging inside a category keeps the consumer's reshape a
    pure regroup.  ``None`` ⇒ merge everything.
    """
    if not dims:
        return ()
    if categories is None:
        categories = [0] * len(dims)
    merged: list[int] = [dims[0]]
    mcat: list[int] = [categories[0]]
    for d, c in zip(dims[1:], categories[1:]):
        if c == mcat[-1]:
            merged[-1] *= d
        else:
            merged.append(d)
            mcat.append(c)
    # trailing merge to reach a well-tiled minor dim (cross-category only
    # when a large buffer would otherwise pad)
    while len(merged) > 1 and merged[-1] < _MIN_MINOR:
        tail = merged.pop()
        merged[-1] *= tail
        mcat.pop()
    return tuple(merged)


def _fused_view(
    edges: list[tuple[int, int]], key: dict[int, tuple]
) -> tuple:
    """Run-fuse one operand for a contraction.

    ``edges``: stored (leg, dim) list.  ``key``: leg → desired sort key;
    contracted legs carry key[0] == 0, free legs key[0] == 1.

    Each operand fuses at its **own** run granularity — the two operands'
    contract parts need not match axis-for-axis, because the executor
    merges every post-perm contract axis into one ``k`` dim (an
    edge-axes reshape, layout-free on TPU) before the dot. The operand's
    **orientation** — contract runs leading ``(k, frees…)`` or trailing
    ``(frees…, k)`` — is chosen per operand: identity permutations win
    outright, otherwise the orientation whose materialized minor dim is
    larger (a ``(k, tiny-frees)`` operand would pad its tiny minor up to
    128 lanes; flipping it to ``(tiny-frees, k)`` stores perfectly).

    Returns: fused view shape, macro perm (or None), dot shape,
    contract_first flag, and the post-perm free (leg-group, dim) list.
    """
    runs: list[list[tuple[int, int]]] = []
    order = {
        leg: i
        for i, (leg, _) in enumerate(sorted(edges, key=lambda e: key[e[0]]))
    }
    for leg, dim in edges:
        if (
            runs
            and order[leg] == order[runs[-1][-1][0]] + 1
            and key[leg][0] == key[runs[-1][-1][0]][0]
        ):
            runs[-1].append((leg, dim))
        else:
            runs.append([(leg, dim)])

    view = tuple(int(math.prod(d for _, d in run)) for run in runs)

    def orientation(contract_first: bool):
        def run_key(i):
            leg_key = key[runs[i][0][0]]
            group = leg_key[0] if contract_first else (1 - leg_key[0])
            return (group, leg_key[1])

        perm_order = sorted(range(len(runs)), key=run_key)
        # Tail guard: the trailing run becomes the materialized minor
        # dim; if it is small and FREE, move the largest free run there
        # (the relayout is paid anyway — keep it well-tiled). Contract
        # runs must never reorder: their merged k-order is the pairing
        # contract with the other operand.
        if (
            perm_order
            and view[perm_order[-1]] < _MIN_MINOR
            and key[runs[perm_order[-1]][0][0]][0] != 0
        ):
            free_idx = [
                i for i in perm_order if key[runs[i][0][0]][0] != 0
            ]
            biggest = max(free_idx, key=lambda i: view[i])
            if biggest != perm_order[-1] and view[biggest] > view[perm_order[-1]]:
                perm_order.remove(biggest)
                perm_order.append(biggest)
        perm: tuple[int, ...] | None = tuple(perm_order)
        if perm == tuple(range(len(runs))):
            perm = None
        minor = view[perm_order[-1]] if perm_order else 1
        return perm_order, perm, minor

    cf = orientation(True)
    cl = orientation(False)
    if cf[1] is None:
        perm_order, perm, contract_first = cf[0], cf[1], True
    elif cl[1] is None:
        perm_order, perm, contract_first = cl[0], cl[1], False
    elif cf[2] >= cl[2]:
        perm_order, perm, contract_first = cf[0], cf[1], True
    else:
        perm_order, perm, contract_first = cl[0], cl[1], False

    k = 1
    free = []
    free_dims = []
    for i in perm_order:
        if key[runs[i][0][0]][0] == 0:
            k *= view[i]
        else:
            free.append(([leg for leg, _ in runs[i]], view[i]))
            free_dims.append(view[i])
    if contract_first:
        dot_shape = (k,) + tuple(free_dims)
    else:
        dot_shape = tuple(free_dims) + (k,)
    return view, perm, dot_shape, contract_first, free


def _staged_pack(edges, contract_order, shared):
    """Leg-granularity replacement pack for an operand whose naive prep
    would tile-pad catastrophically. Target flat order: the agreed
    k-order, then free legs in stored order. Returns
    ``(view, perm, dot, cfirst, free, ops)`` — the (view, perm) pair is
    the host oracle's equivalent naive prep — or ``None`` when the
    permutation isn't stageable (fall back to naive)."""
    stored = [leg for leg, _ in edges]
    dims = [d for _, d in edges]
    spos = {l: i for i, l in enumerate(stored)}
    free_legs = [l for l in stored if l not in shared]
    k = int(math.prod(dims[spos[l]] for l in contract_order))
    f = int(math.prod(dims[spos[l]] for l in free_legs))
    # orientation by materialized minor: a (k, tiny-f) operand would
    # lane-pad every add/dot buffer 32x (catastrophic under vmap, where
    # XLA can't always fuse it away) — put the bigger side trailing
    cfirst = f >= _MIN_MINOR or f >= k
    if cfirst:
        target = list(contract_order) + free_legs
        dot = (k, max(f, 1))
    else:
        target = free_legs + list(contract_order)
        dot = (max(f, 1), k)
    perm = [spos[l] for l in target]
    ops = _staged_ops(dims, perm)
    if ops is None:
        return None
    free = [(free_legs, f)] if free_legs else []
    return (tuple(dims), tuple(perm), dot, cfirst, free, ops)


_INF_DEATH = 1 << 60


def _pair_step(
    lhs: int,
    rhs: int,
    ta: LeafTensor,
    tb: LeafTensor,
    death: dict[int, int] | None = None,
) -> tuple[PairStep, LeafTensor]:
    """Build one contraction step.

    ``ta``/``tb`` carry each operand's legs in **stored buffer order**.
    Free legs keep that order (see `_fused_view`); ``death`` (leg → index
    of the future step that contracts it) is used to stop storage merges
    at the immediate consumer's contract/keep boundary, so the consumer's
    reshape stays a layout-free regroup.
    """
    a_edges = list(ta.edges())
    b_edges = list(tb.edges())
    a_set = {leg for leg, _ in a_edges}
    b_set = {leg for leg, _ in b_edges}
    shared = a_set & b_set
    if death is None:
        death = {}

    def build(contract_order):
        """Candidate step for one agreed k-order. Cost models the data
        movement: each operand that needs a transpose pays its size
        times the tile-padding penalty of the materialized output."""
        cpos = {leg: i for i, leg in enumerate(contract_order)}

        def keys(edges):
            key: dict[int, tuple] = {}
            for pos, (leg, _) in enumerate(edges):
                if leg in shared:
                    key[leg] = (0, cpos[leg])
                else:
                    # frees keep stored order: no merge-shuffle ever
                    # builds up, and the contract extraction is a
                    # leading-dim row gather over the intact tail
                    key[leg] = (1, pos)
            return key

        a = _fused_view(a_edges, keys(a_edges))
        b = _fused_view(b_edges, keys(b_edges))
        cost = 0.0
        for view, perm, _, _, _ in (a, b):
            if perm is None:
                continue
            size = float(math.prod(view)) if view else 1.0
            minor = view[perm[-1]] if perm else 1
            penalty = (_MIN_MINOR / minor) if minor < _MIN_MINOR else 1.0
            cost += size * penalty
        return a, b, cost

    # the agreed k-order makes one operand's contract part contiguous in
    # its own storage while the other pays a relayout — try both and
    # keep the cheaper (big x big joins would otherwise shuffle the
    # wrong side; see step-cost model in `build`)
    order_a = [leg for leg, _ in a_edges if leg in shared]
    order_b = [leg for leg, _ in b_edges if leg in shared]
    cand_a = build(order_a)
    if order_a == order_b:
        best, korder = cand_a, order_a
    else:
        cand_b = build(order_b)
        best, korder = (
            (cand_a, order_a) if cand_a[2] <= cand_b[2] else (cand_b, order_b)
        )
    (a_view, a_perm, a_dot, a_cfirst, a_free) = best[0]
    (b_view, b_perm, b_dot, b_cfirst, b_free) = best[1]
    # operands whose naive prep would tile-pad catastrophically switch to
    # the staged plan (leg granularity, minor >= 128 at every step)
    a_ops = b_ops = None
    if _naive_prep_bad(a_view, a_perm):
        staged = _staged_pack(a_edges, korder, shared)
        if staged is not None:
            (a_view, a_perm, a_dot, a_cfirst, a_free, a_ops) = staged
    if _naive_prep_bad(b_view, b_perm):
        staged = _staged_pack(b_edges, korder, shared)
        if staged is not None:
            (b_view, b_perm, b_dot, b_cfirst, b_free, b_ops) = staged
    a_k = a_dot[0] if a_cfirst else a_dot[-1]
    b_k = b_dot[0] if b_cfirst else b_dot[-1]
    assert a_k == b_k, "contract dims must agree"

    # orientation: the dot-rhs supplies the output's trailing dims — pick
    # the operand with the larger trailing free run so the stored result
    # keeps a well-tiled minor dim.
    a_tail = a_free[-1][1] if a_free else 1
    b_tail = b_free[-1][1] if b_free else 1
    swap = a_tail > b_tail

    first, second = (b_free, a_free) if swap else (a_free, b_free)
    out_legs = [leg for legs, _ in first for leg in legs] + [
        leg for legs, _ in second for leg in legs
    ]
    dim_of = {leg: d for leg, d in a_edges}
    dim_of.update({leg: d for leg, d in b_edges})
    out_dims = [dim_of[leg] for leg in out_legs]

    # storage merge boundary: the immediate consumer's contract set = the
    # earliest-dying cohort among the output legs. Categorize at LEG
    # granularity (a fused run can mix cohorts) so merges never cross the
    # consumer's contract/keep split.
    consumer_step = min(
        (death.get(leg, _INF_DEATH) for leg in out_legs), default=_INF_DEATH
    )
    out_leg_cat = [
        0 if death.get(leg, _INF_DEATH) == consumer_step else 1
        for leg in out_legs
    ]
    out_store = _storage_merge(list(out_dims), out_leg_cat)
    if not out_store:
        out_store = (1,)

    step = PairStep(
        lhs=lhs,
        rhs=rhs,
        a_view=a_view,
        a_perm=a_perm,
        a_dot=a_dot,
        a_cfirst=a_cfirst,
        b_view=b_view,
        b_perm=b_perm,
        b_dot=b_dot,
        b_cfirst=b_cfirst,
        swap=swap,
        out_store=out_store,
        a_ops=a_ops,
        b_ops=b_ops,
    )
    return step, LeafTensor(out_legs, out_dims)


@dataclass(frozen=True)
class ContractionProgram:
    """A compiled contraction path over ``num_inputs`` flat leaf slots."""

    num_inputs: int
    steps: tuple[PairStep, ...]
    result_slot: int
    result_legs: tuple[int, ...]
    result_shape: tuple[int, ...]
    stored_result_shape: tuple[int, ...] = ()
    # reference leg order (the ``^``-fold, ``contraction.rs:70-86``);
    # public APIs permute the buffer to this order host-side
    canonical_legs: tuple[int, ...] = ()

    def __post_init__(self):
        if not self.stored_result_shape:
            object.__setattr__(
                self,
                "stored_result_shape",
                self.steps[-1].out_store if self.steps else self.result_shape,
            )
        if not self.canonical_legs:
            object.__setattr__(self, "canonical_legs", self.result_legs)

    def canonical_perm(self) -> tuple[int, ...] | None:
        """Axis permutation taking the result buffer (``result_legs``
        order) to the reference's canonical order, or None if identity."""
        if self.canonical_legs == self.result_legs:
            return None
        pos = {leg: i for i, leg in enumerate(self.result_legs)}
        return tuple(pos[leg] for leg in self.canonical_legs)

    def signature(self) -> tuple:
        """Hashable identity for jit-compilation caching. ``result_shape``
        matters: two zero-step programs with different shapes must not
        share a key."""
        return (self.num_inputs, self.steps, self.result_slot, self.result_shape)

    def signature_digest(self) -> str:
        """Stable hex digest of :meth:`signature` via the shared
        canonical encoder — the form persisted by on-disk artifacts
        (serving plan cache, checkpoint signatures) where the in-memory
        tuple cannot be stored."""
        from tnc_tpu.utils.digest import stable_digest

        return stable_digest(self.signature())


def build_program(tn: CompositeTensor, contract_path: ContractionPath) -> ContractionProgram:
    """Compile a (possibly nested) replace-left path over ``tn`` into a flat
    program. Nested children are flattened: their leaves receive global
    slots and their nested paths are inlined before the toplevel pairs,
    preserving the reference's contract-children-first order
    (``contraction.rs:42-49``).

    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    >>> c = Circuit(); reg = c.allocate_register(3)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> for i in range(2):
    ...     c.append_gate(TensorData.gate("cx"), [reg.qubit(i), reg.qubit(i + 1)])
    >>> tn, _ = c.into_amplitude_network("111")
    >>> path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    >>> program = build_program(tn, path)
    >>> program.num_inputs, len(program.steps), program.result_shape
    (9, 8, ())
    """
    flat_slots: list[LeafTensor] = []
    # (lhs_slot, rhs_slot, lhs_legs, rhs_legs) per step, for the
    # consumer-alignment pass (leg sets are layout-independent).
    step_plan: list[tuple[int, int, frozenset[int], frozenset[int]]] = []

    def compile_composite(
        tensors: list[Tensor], cpath: ContractionPath
    ) -> tuple[int, LeafTensor]:
        """Returns the global slot holding this subnetwork's result and the
        result's metadata (leg-set level; buffer order is resolved in the
        second pass)."""
        slot_of: list[int] = []
        current: list[LeafTensor | None] = []
        for child in tensors:
            if isinstance(child, CompositeTensor):
                slot_of.append(-1)  # filled by nested compilation below
                current.append(None)
            else:
                slot = len(flat_slots)
                flat_slots.append(child)
                slot_of.append(slot)
                current.append(child)

        for i in sorted(cpath.nested):
            nested_path = cpath.nested[i]
            child = tensors[i]
            if not isinstance(child, CompositeTensor):
                raise TypeError(f"nested path at index {i} targets a leaf")
            slot, child_result = compile_composite(child.tensors, nested_path)
            slot_of[i] = slot
            current[i] = child_result

        for idx, child in enumerate(tensors):
            if isinstance(child, CompositeTensor) and slot_of[idx] == -1:
                raise ValueError(
                    f"composite child {idx} has no nested contraction path"
                )

        for i, j in cpath.toplevel:
            ta, tb = current[i], current[j]
            if ta is None or tb is None:
                raise ValueError(f"path step ({i}, {j}) uses a consumed tensor")
            step_plan.append(
                (
                    slot_of[i],
                    slot_of[j],
                    frozenset(ta.legs),
                    frozenset(tb.legs),
                )
            )
            # metadata only — the real PairSteps are built in the
            # consumer-aligned pass below (leg order there is free)
            current[i] = ta ^ tb
            current[j] = None

        survivors = [idx for idx, t in enumerate(current) if t is not None]
        if len(survivors) != 1:
            raise ValueError(
                f"path does not fully contract: {len(survivors)} tensors remain"
            )
        survivor = survivors[0]
        result = current[survivor]
        assert result is not None
        return slot_of[survivor], result

    result_slot, final = compile_composite(list(tn.tensors), contract_path)

    # Death-schedule pass: a leg of a tree-shaped path is contracted at
    # exactly one step. _pair_step uses the death times to stop storage
    # merges at each buffer's immediate consumer's contract/keep boundary
    # (see _pair_step docstring).
    death: dict[int, int] = {}
    for t, (_, _, t_la, t_lb) in enumerate(step_plan):
        for leg in t_la & t_lb:
            death[leg] = t

    steps: list[PairStep] = []
    meta: dict[int, LeafTensor] = {
        slot: leaf for slot, leaf in enumerate(flat_slots)
    }
    canonical = final  # pass-1 ^-fold order (reference semantics)
    for lhs_slot, rhs_slot, _, _ in step_plan:
        step, result = _pair_step(
            lhs_slot, rhs_slot, meta[lhs_slot], meta[rhs_slot], death
        )
        steps.append(step)
        meta[lhs_slot] = result
    final = meta[result_slot] if step_plan else final

    return ContractionProgram(
        num_inputs=len(flat_slots),
        steps=tuple(steps),
        result_slot=result_slot,
        result_legs=tuple(final.legs),
        result_shape=tuple(final.bond_dims),
        canonical_legs=tuple(canonical.legs),
    )


def flat_leaf_tensors(tn: CompositeTensor) -> list[LeafTensor]:
    """Leaves of ``tn`` in the same order `build_program` assigns slots."""
    out: list[LeafTensor] = []

    def visit(tensors: list[Tensor]) -> None:
        for child in tensors:
            if not isinstance(child, CompositeTensor):
                out.append(child)
        for child in tensors:
            if isinstance(child, CompositeTensor):
                visit(child.tensors)

    visit(list(tn.tensors))
    return out
