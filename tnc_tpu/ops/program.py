"""Contraction-path → static execution program compiler.

The reference executes a path as a loop of TBLIS einsum calls, one per pair
(``tnc/src/tensornetwork/contraction.rs:52-57,88-116``). On TPU, the whole
path is known before execution and every shape is static, so we compile it
once into a :class:`ContractionProgram`: a flat list of
transpose→reshape→matmul→reshape steps. This form

- maps every pairwise contraction onto the MXU as a single matmul,
- avoids einsum-label limits for high-rank tensors (statevector networks
  can exceed 50 open legs),
- is traceable by ``jax.jit`` as one XLA program, so intermediates stay in
  HBM, elementwise glue is fused, and buffers are freed eagerly
  (the reference frees inputs per step via ``Option::take``,
  ``contraction.rs:39,53-56``; XLA liveness analysis does the same here).

A pairwise contraction of ``a`` (legs La) and ``b`` (legs Lb) with shared
legs S = La∩Lb computes ``out = a_keep × S · S × b_keep`` and produces the
legs ``(La-Lb) ++ (Lb-La)`` — exactly the reference's ``a ^ b`` ordering,
so no extra transpose is needed afterwards.
"""

from __future__ import annotations

from dataclasses import dataclass

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor, Tensor


@dataclass(frozen=True)
class PairStep:
    """One pairwise contraction, fully shape-resolved.

    ``*_perm`` are the logical (per-leg) permutations; executors use the
    fused ``*_pre``/``*_mperm`` forms instead: the logical permutation
    with runs of consecutive source axes that stay consecutive collapsed
    into single macro axes. Quantum-circuit tensors are high-rank with
    all-dim-2 legs (rank 25+ after slicing Sycamore-53), and the TPU
    compiler blows up on rank-20+ transposes, while the fused macro
    transpose is typically rank <= 8 over the same elements. Device
    buffers hold each intermediate as its (m, n) matmul result — the
    high-rank logical shape never materializes on device.
    """

    lhs: int  # slot of left input (result replaces this slot)
    rhs: int  # slot of right input (freed after the step)
    lhs_perm: tuple[int, ...]  # transpose to (keep…, shared…)
    rhs_perm: tuple[int, ...]  # transpose to (shared…, keep…)
    lhs_mat: tuple[int, int]  # (m, k) matmul view of lhs
    rhs_mat: tuple[int, int]  # (k, n) matmul view of rhs
    out_shape: tuple[int, ...]  # final result shape for this step
    lhs_pre: tuple[int, ...] = ()  # fused reshape before macro transpose
    lhs_mperm: tuple[int, ...] = ()  # macro transpose
    rhs_pre: tuple[int, ...] = ()
    rhs_mperm: tuple[int, ...] = ()


def _fuse_perm(
    dims: tuple[int, ...], perm: tuple[int, ...]
) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Run-length fuse a permutation: maximal runs of consecutive source
    axes that appear consecutively in ``perm`` become one macro axis.
    Returns (pre_shape in source order, macro permutation)."""
    if not perm:
        return (1,), (0,)
    runs: list[list[int]] = [[perm[0]]]
    for p in perm[1:]:
        if p == runs[-1][-1] + 1:
            runs[-1].append(p)
        else:
            runs.append([p])
    source_order = sorted(range(len(runs)), key=lambda r: runs[r][0])
    pre_shape = []
    for ri in source_order:
        d = 1
        for p in runs[ri]:
            d *= dims[p]
        pre_shape.append(d)
    pos_in_source = {ri: k for k, ri in enumerate(source_order)}
    macro_perm = tuple(pos_in_source[ri] for ri in range(len(runs)))
    return tuple(pre_shape), macro_perm


@dataclass(frozen=True)
class ContractionProgram:
    """A compiled contraction path over ``num_inputs`` flat leaf slots."""

    num_inputs: int
    steps: tuple[PairStep, ...]
    result_slot: int
    result_legs: tuple[int, ...]
    result_shape: tuple[int, ...]

    def signature(self) -> tuple:
        """Hashable identity for jit-compilation caching. ``result_shape``
        matters: the jitted body reshapes the final buffer to it, so two
        zero-step programs with different shapes must not share a key."""
        return (self.num_inputs, self.steps, self.result_slot, self.result_shape)


def _pair_step(
    lhs: int,
    rhs: int,
    ta: LeafTensor,
    tb: LeafTensor,
    next_shared: set[int] | None = None,
) -> tuple[PairStep, LeafTensor]:
    """Build one contraction step.

    ``next_shared``: the legs of this step's *output* that its consumer
    step will contract away. When known, both keep-groups are emitted as
    [kept-by-consumer…, contracted-by-consumer…] (sorted by leg id within
    each), so the consumer's transpose degrades from a per-leg
    interleave (rank ~ tensor rank) to a handful of contiguous segments
    — the reorder is free here because it rides this step's transpose.
    """
    b_leg_set = set(tb.legs)
    a_leg_set = set(ta.legs)

    a_keep = [(pos, leg, dim) for pos, (leg, dim) in enumerate(ta.edges()) if leg not in b_leg_set]
    a_shared = [(pos, leg, dim) for pos, (leg, dim) in enumerate(ta.edges()) if leg in b_leg_set]
    b_keep = [(pos, leg, dim) for pos, (leg, dim) in enumerate(tb.edges()) if leg not in a_leg_set]

    if next_shared is not None:
        group = lambda item: (item[1] in next_shared, item[1])  # noqa: E731
        a_keep.sort(key=group)
        b_keep.sort(key=group)

    # The k-dimension needs one common shared-leg order. Follow the
    # *larger* operand's axis order: its shared segment then stays
    # contiguous (cheap transpose on the expensive tensor) and only the
    # smaller operand pays the interleaved reorder.
    b_pos_of_leg = {leg: pos for pos, leg in enumerate(tb.legs)}
    if tb.size() > ta.size():
        b_shared = [
            (pos, leg, dim)
            for pos, (leg, dim) in enumerate(tb.edges())
            if leg in a_leg_set
        ]
        a_pos_of_leg = {leg: pos for pos, leg in enumerate(ta.legs)}
        a_dim_of_leg = {leg: dim for leg, dim in ta.edges()}
        a_shared = [
            (a_pos_of_leg[leg], leg, a_dim_of_leg[leg])
            for (_, leg, _) in b_shared
        ]
    else:
        b_shared = [(b_pos_of_leg[leg], leg, dim) for (_, leg, dim) in a_shared]

    m = 1
    for _, _, d in a_keep:
        m *= d
    k = 1
    for _, _, d in a_shared:
        k *= d
    n = 1
    for _, _, d in b_keep:
        n *= d

    lhs_perm = tuple(p for p, _, _ in a_keep) + tuple(p for p, _, _ in a_shared)
    rhs_perm = tuple(p for p, _, _ in b_shared) + tuple(p for p, _, _ in b_keep)

    out_legs = [leg for _, leg, _ in a_keep] + [leg for _, leg, _ in b_keep]
    out_dims = [dim for _, _, dim in a_keep] + [dim for _, _, dim in b_keep]
    result = LeafTensor(out_legs, out_dims)

    a_dims = tuple(d for _, d in ta.edges())
    b_dims = tuple(d for _, d in tb.edges())
    lhs_pre, lhs_mperm = _fuse_perm(a_dims, lhs_perm)
    rhs_pre, rhs_mperm = _fuse_perm(b_dims, rhs_perm)

    step = PairStep(
        lhs=lhs,
        rhs=rhs,
        lhs_perm=lhs_perm,
        rhs_perm=rhs_perm,
        lhs_mat=(m, k),
        rhs_mat=(k, n),
        out_shape=tuple(out_dims),
        lhs_pre=lhs_pre,
        lhs_mperm=lhs_mperm,
        rhs_pre=rhs_pre,
        rhs_mperm=rhs_mperm,
    )
    return step, result


def build_program(tn: CompositeTensor, contract_path: ContractionPath) -> ContractionProgram:
    """Compile a (possibly nested) replace-left path over ``tn`` into a flat
    program. Nested children are flattened: their leaves receive global
    slots and their nested paths are inlined before the toplevel pairs,
    preserving the reference's contract-children-first order
    (``contraction.rs:42-49``).
    """
    flat_slots: list[LeafTensor] = []
    # (lhs_slot, rhs_slot, lhs_legs, rhs_legs) per step, for the
    # consumer-alignment pass (leg sets are layout-independent).
    step_plan: list[tuple[int, int, frozenset[int], frozenset[int]]] = []

    def compile_composite(
        tensors: list[Tensor], cpath: ContractionPath
    ) -> tuple[int, LeafTensor]:
        """Returns the global slot holding this subnetwork's result and the
        result's metadata in the slot buffer's *actual* axis order (the fold
        of ``^`` along this path — NOT ``external_tensor()``, whose leg
        order follows child order instead of contraction order)."""
        slot_of: list[int] = []
        current: list[LeafTensor | None] = []
        for child in tensors:
            if isinstance(child, CompositeTensor):
                slot_of.append(-1)  # filled by nested compilation below
                current.append(None)
            else:
                slot = len(flat_slots)
                flat_slots.append(child)
                slot_of.append(slot)
                current.append(child)

        for i in sorted(cpath.nested):
            nested_path = cpath.nested[i]
            child = tensors[i]
            if not isinstance(child, CompositeTensor):
                raise TypeError(f"nested path at index {i} targets a leaf")
            slot, child_result = compile_composite(child.tensors, nested_path)
            slot_of[i] = slot
            current[i] = child_result

        for idx, child in enumerate(tensors):
            if isinstance(child, CompositeTensor) and slot_of[idx] == -1:
                raise ValueError(
                    f"composite child {idx} has no nested contraction path"
                )

        for i, j in cpath.toplevel:
            ta, tb = current[i], current[j]
            if ta is None or tb is None:
                raise ValueError(f"path step ({i}, {j}) uses a consumed tensor")
            step_plan.append(
                (
                    slot_of[i],
                    slot_of[j],
                    frozenset(ta.legs),
                    frozenset(tb.legs),
                )
            )
            # metadata only — the real PairSteps are built in the
            # consumer-aligned pass below (leg order there is free)
            current[i] = ta ^ tb
            current[j] = None

        survivors = [idx for idx, t in enumerate(current) if t is not None]
        if len(survivors) != 1:
            raise ValueError(
                f"path does not fully contract: {len(survivors)} tensors remain"
            )
        survivor = survivors[0]
        result = current[survivor]
        assert result is not None
        return slot_of[survivor], result

    result_slot, final = compile_composite(list(tn.tensors), contract_path)

    # Consumer-alignment pass: each step's output is consumed by exactly
    # one later step (the path is a tree); knowing which of its legs that
    # consumer contracts lets _pair_step group them contiguously, keeping
    # every transpose low-rank after run fusion (see PairStep docstring).
    n_steps = len(step_plan)
    next_shared: list[set[int] | None] = [None] * n_steps
    producer: dict[int, int] = {}  # slot -> step index of current content
    for t, (t_lhs, t_rhs, t_la, t_lb) in enumerate(step_plan):
        s = producer.get(t_lhs)
        if s is not None:
            next_shared[s] = set((step_plan[s][2] ^ step_plan[s][3]) & t_lb)
        s = producer.get(t_rhs)
        if s is not None:
            next_shared[s] = set((step_plan[s][2] ^ step_plan[s][3]) & t_la)
        producer[t_lhs] = t

    steps: list[PairStep] = []
    meta: dict[int, LeafTensor] = {
        slot: leaf for slot, leaf in enumerate(flat_slots)
    }
    for s, (lhs_slot, rhs_slot, _, _) in enumerate(step_plan):
        step, result = _pair_step(
            lhs_slot, rhs_slot, meta[lhs_slot], meta[rhs_slot], next_shared[s]
        )
        steps.append(step)
        meta[lhs_slot] = result
    final = meta[result_slot] if step_plan else final

    return ContractionProgram(
        num_inputs=len(flat_slots),
        steps=tuple(steps),
        result_slot=result_slot,
        result_legs=tuple(final.legs),
        result_shape=tuple(final.bond_dims),
    )


def flat_leaf_tensors(tn: CompositeTensor) -> list[LeafTensor]:
    """Leaves of ``tn`` in the same order `build_program` assigns slots."""
    out: list[LeafTensor] = []

    def visit(tensors: list[Tensor]) -> None:
        for child in tensors:
            if not isinstance(child, CompositeTensor):
                out.append(child)
        for child in tensors:
            if isinstance(child, CompositeTensor):
                visit(child.tensors)

    visit(list(tn.tensors))
    return out
