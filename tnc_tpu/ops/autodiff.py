"""Differentiable contraction — a capability the reference cannot offer.

Everything the executor runs is a chain of jittable dots, so JAX
differentiates a whole contraction for free. The natural applications
are variational quantum circuits: the gradient of an expectation value
⟨ψ(θ)|O|ψ(θ)⟩ (or of a single amplitude) with respect to selected leaf
tensors — e.g. parameterized gate matrices — comes from one
reverse-mode sweep over the same compiled program instead of
parameter-shift re-contractions.

Complex leaves follow JAX's reverse-mode convention for real-valued
``f``: the returned cotangent ``g`` of leaf ``T`` satisfies
``df = Re(sum(g * dT))`` for a perturbation ``dT`` (validated entrywise
against finite differences in ``tests/test_autodiff.py``). ``scalar_fn``
defaults to the real part of the fully-contracted scalar.

The reference's Rust stack has no autodiff; this closes the variational
workflow gap TPU-natively (listed as item 4 of docs/future_work.md).
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.ops.backends import _run_steps
from tnc_tpu.ops.program import build_program, flat_leaf_tensors
from tnc_tpu.tensornetwork.tensor import CompositeTensor


def _validate_wrt(wrt, n_slots: int) -> list[int]:
    """Flat-slot list for differentiation: in range (no negative
    indexing — slots are flat leaf indices) and duplicate-free (a
    duplicate would shadow the previous tracer and silently yield a
    zero gradient for every occurrence but the last)."""
    wrt = list(wrt)
    if len(set(wrt)) != len(wrt):
        raise ValueError("duplicate slots in wrt")
    for s in wrt:
        if not 0 <= s < n_slots:
            raise ValueError(f"wrt slot {s} out of range 0..{n_slots - 1}")
    return wrt


def contraction_value_and_grad(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    wrt: Sequence[int] | None = None,
    scalar_fn: Callable | None = None,
    dtype: str = "complex64",
):
    """Value and gradient of a contraction w.r.t. selected leaf tensors.

    ``wrt``: flat leaf-slot indices (see `flat_leaf_tensors` order);
    default: all leaves. ``scalar_fn``: maps the (complex) result array
    to a real scalar; default takes the real part of the first element
    (an amplitude/expectation network contracts to a scalar).

    Returns ``(value, grads)`` where ``value`` is the full complex
    result (host array, canonical shape) and ``grads[i]`` is the
    cotangent for ``wrt[i]``, shaped like that leaf.

    The gradient runs through the same whole-path program the forward
    pass uses — no parameter-shift re-contractions. Donation is off (the
    reverse sweep needs the primals).

    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    >>> c = Circuit(); reg = c.allocate_register(3)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> for i in range(2):
    ...     c.append_gate(TensorData.gate("cx"), [reg.qubit(i), reg.qubit(i + 1)])
    >>> tn, _ = c.into_amplitude_network("111")
    >>> path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    >>> value, grads = contraction_value_and_grad(tn, path, wrt=[0])
    >>> abs(complex(value.reshape(-1)[0]) - 2 ** -0.5) < 1e-6
    True
    >>> grads[0].shape   # cotangent shaped like leaf 0
    (2,)
    """
    import jax
    import jax.numpy as jnp

    program = build_program(tn, contract_path)
    leaves = flat_leaf_tensors(tn)
    arrays = [
        jnp.asarray(leaf.data.into_data(), dtype=dtype) for leaf in leaves
    ]
    if wrt is None:
        wrt = list(range(len(arrays)))
    wrt = _validate_wrt(wrt, len(arrays))

    if scalar_fn is None:

        def scalar_fn(result):
            return jnp.real(result.reshape(-1)[0])

    perm = program.canonical_perm()
    dim_of = dict(zip(program.result_legs, program.result_shape))
    canonical_shape = tuple(dim_of[leg] for leg in program.canonical_legs)

    def forward(diff_arrays):
        buffers = list(arrays)
        for slot, arr in zip(wrt, diff_arrays):
            buffers[slot] = arr
        out = _run_steps(jnp, program, buffers).reshape(program.result_shape)
        if perm is not None:
            out = jnp.transpose(out, perm)
        return scalar_fn(out), out

    diff_in = tuple(arrays[slot] for slot in wrt)
    (value_scalar, result), grads = jax.value_and_grad(
        forward, has_aux=True
    )(diff_in)
    del value_scalar
    return (
        np.asarray(result).reshape(canonical_shape),
        [np.asarray(g) for g in grads],
    )


def sliced_contraction_value_and_grad(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    slicing,
    wrt: Sequence[int] | None = None,
    scalar_fn: Callable | None = None,
    dtype: str = "complex64",
):
    """Like :func:`contraction_value_and_grad` for a *sliced* plan: the
    value is the sum over all slice programs, and one reverse-mode sweep
    through the on-device slice loop yields the gradients — the vjp of
    the slice sum is the sum of per-slice vjps, so memory stays at the
    sliced peak (the whole point of slicing) instead of the unsliced
    program's. Closes the "gradients through sliced programs" item of
    docs/future_work.md (#4).

    The slice loop is a ``lax.fori_loop`` with static bounds, which JAX
    converts to a scan for reverse-mode; the body is ``jax.checkpoint``-
    ed so the backward pass stores only the loop carry and recomputes
    per-slice intermediates (without remat, scan-grad stacks every
    slice's residuals — exactly the memory slicing exists to avoid).
    Slice contributions accumulate with the same Kahan compensation as
    the forward executors. Complex dtype path (like the unsliced
    version): run on CPU/``jax64`` for gradient workflows.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tnc_tpu.ops.sliced import (
        _slice_indices,
        build_sliced_program,
        index_buffer,
        kahan_add,
    )

    sp = build_sliced_program(tn, contract_path, slicing)
    leaves = flat_leaf_tensors(tn)
    arrays = [
        jnp.asarray(leaf.data.into_data(), dtype=dtype) for leaf in leaves
    ]
    if wrt is None:
        wrt = list(range(len(arrays)))
    wrt = _validate_wrt(wrt, len(arrays))

    if scalar_fn is None:

        def scalar_fn(result):
            return jnp.real(result.reshape(-1)[0])

    program = sp.program
    perm = program.canonical_perm()
    dim_of = dict(zip(program.result_legs, program.result_shape))
    canonical_shape = tuple(dim_of[leg] for leg in program.canonical_legs)
    num = sp.slicing.num_slices

    def forward(diff_arrays):
        buffers = list(arrays)
        for slot, arr in zip(wrt, diff_arrays):
            buffers[slot] = arr

        @jax.checkpoint
        def contribution(s):
            indices = _slice_indices(sp.slicing, s)
            sliced = [
                index_buffer(jnp, arr, info, indices)
                for arr, info in zip(buffers, sp.slot_slices)
            ]
            return _run_steps(jnp, program, list(sliced))

        def body(s, carry):
            return kahan_add(carry[0], carry[1], contribution(s))

        zeros = jnp.zeros(program.stored_result_shape, dtype=dtype)
        acc, comp = lax.fori_loop(0, num, body, (zeros, zeros))
        out = (acc + comp).reshape(program.result_shape)
        if perm is not None:
            out = jnp.transpose(out, perm)
        return scalar_fn(out), out

    diff_in = tuple(arrays[slot] for slot in wrt)
    (value_scalar, result), grads = jax.value_and_grad(
        forward, has_aux=True
    )(diff_in)
    del value_scalar
    return (
        np.asarray(result).reshape(canonical_shape),
        [np.asarray(g) for g in grads],
    )
