"""Split-complex execution: complex tensors as (real, imag) float pairs.

The TPU's MXU is a real-arithmetic systolic array, and this stack exposes
no complex dtypes at all — so the TPU path represents every tensor as two
float32 arrays and lowers each pairwise contraction to **three** real
matmuls via the Gauss/Karatsuba identity (25% fewer flops than the naive
four):

    k1 = (ar + ai) @ br
    k2 = ar @ (bi - br)
    k3 = ai @ (br + bi)
    real = k1 - k3,  imag = k1 + k2

This is the "split real/imag representation" contingency the survey
flagged for TPU complex support (SURVEY.md §7 hard parts), promoted to
the primary device layout. Host-side data stays complex128; the split
happens at the host→device boundary.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from tnc_tpu.ops.program import ContractionProgram, PairStep


def split_array(array: np.ndarray, dtype: str = "float32") -> tuple[np.ndarray, np.ndarray]:
    array = np.asarray(array)
    return (
        np.ascontiguousarray(array.real, dtype=dtype),
        np.ascontiguousarray(array.imag, dtype=dtype),
    )


def combine_array(re: Any, im: Any) -> np.ndarray:
    return np.asarray(re) + 1j * np.asarray(im)


def gauss_matmul(xp, ar, ai, br, bi, precision=None):
    """Complex matmul on split parts with 3 real matmuls."""
    if precision is None:
        k1 = xp.matmul(ar + ai, br)
        k2 = xp.matmul(ar, bi - br)
        k3 = xp.matmul(ai, br + bi)
    else:
        k1 = xp.matmul(ar + ai, br, precision=precision)
        k2 = xp.matmul(ar, bi - br, precision=precision)
        k3 = xp.matmul(ai, br + bi, precision=precision)
    return k1 - k3, k1 + k2


def _prep(xp, part, pre: tuple[int, ...], mperm: tuple[int, ...], mat: tuple[int, int]):
    # fused low-rank transpose (see PairStep docstring)
    return xp.transpose(part.reshape(pre), mperm).reshape(mat)


def apply_step_split(xp, apair, bpair, step, precision=None):
    """Split-complex analogue of ``backends.apply_step``: one pairwise
    contraction of (real, imag) pairs via three real matmuls. The single
    source of truth shared by every split-mode executor."""
    ar = _prep(xp, apair[0], step.lhs_pre, step.lhs_mperm, step.lhs_mat)
    ai = _prep(xp, apair[1], step.lhs_pre, step.lhs_mperm, step.lhs_mat)
    br = _prep(xp, bpair[0], step.rhs_pre, step.rhs_mperm, step.rhs_mat)
    bi = _prep(xp, bpair[1], step.rhs_pre, step.rhs_mperm, step.rhs_mat)
    return gauss_matmul(xp, ar, ai, br, bi, precision)


def run_steps_split(
    xp,
    program: ContractionProgram,
    buffers: list[tuple[Any, Any] | None],
    precision=None,
):
    """Split-complex analogue of ``backends._run_steps``; ``buffers`` are
    (real, imag) pairs and the result is a pair. Intermediates stay
    matrix-shaped between steps."""
    for step in program.steps:
        buffers[step.lhs] = apply_step_split(
            xp, buffers[step.lhs], buffers[step.rhs], step, precision
        )
        buffers[step.rhs] = None
    re, im = buffers[program.result_slot]
    return re.reshape(program.result_shape), im.reshape(program.result_shape)
