"""Split-complex execution: complex tensors as (real, imag) float pairs.

The TPU's MXU is a real-arithmetic systolic array, and this stack exposes
no complex dtypes at all — so the TPU path represents every tensor as two
float32 arrays and lowers each pairwise contraction to **three** real
matmuls via the Gauss/Karatsuba identity (25% fewer flops than the naive
four):

    k1 = (ar + ai) @ br
    k2 = ar @ (bi - br)
    k3 = ai @ (br + bi)
    real = k1 - k3,  imag = k1 + k2

This is the "split real/imag representation" contingency the survey
flagged for TPU complex support (SURVEY.md §7 hard parts), promoted to
the primary device layout. Host-side data stays complex128; the split
happens at the host→device boundary.
"""

from __future__ import annotations

import logging
import math
import os
from dataclasses import dataclass
from typing import Any

import numpy as np

from tnc_tpu.ops.program import ContractionProgram

logger = logging.getLogger(__name__)

#: kernel modes a single step can execute under. ``chain`` is not a
#: per-step mode — chained steps run ``naive`` arithmetic inside one
#: fused multi-step dispatch (see :class:`KernelPolicy`).
KERNEL_MODES = (
    "naive", "gauss", "fused", "fused_transpose", "strassen", "chain", "auto",
)

#: real-multiply credit of each kernel mode relative to the naive
#: 4-dot complex lowering (the unit every flop count in the stack
#: uses): Gauss runs 3 of the 4 dots, one Strassen level on top of
#: Gauss runs 21 half-size sub-GEMMs against naive's 32 half-units.
#: ``bench.py`` multiplies by these so per-bucket MFU stays comparable
#: across kernel modes (effective-flop crediting).
EFFECTIVE_FLOP_FACTOR = {
    "naive": 1.0,
    "fused": 1.0,  # naive arithmetic, fewer HBM passes
    "fused_transpose": 1.0,  # naive arithmetic, no transpose HBM pass
    "gauss": 0.75,
    "strassen": 21.0 / 32.0,  # gauss × one Strassen level
}

#: dot-precision rungs a step can run under on the bf16 MXU (f32 dots
#: are emulated in bf16 passes): ``highest`` = the 6-pass bf16x6
#: recomposition (closest to true f32 — the backend's ``float32``
#: default), ``high`` = the 3-pass bf16x3 (≈2× dot throughput at
#: ≈2^-21 per-product relative error; the rung
#: ``scripts/hw_campaign2.sh`` step 1b A/Bs and
#: ``scripts/precision_parity_smoke.py`` pins numerically).
DOT_PRECISION_MODES = ("highest", "high")

#: documented per-dot relative-error rung of bf16x3 (``high``): the
#: 3-term recomposition drops the mid·mid and lo cross products, so
#: its error floor is ~2^-18 relative to the result magnitude —
#: measured per bucket k-length at ≤5.3e-6 by
#: ``scripts/precision_parity_smoke.py`` (the CI half of
#: ``hw_campaign2.sh`` step 1b). :func:`plan_precision_modes` only
#: promotes when the run's parity budget clears this rung with 2×
#: headroom; the hardware campaign's slice-subset parity oracle stays
#: the final gate.
HIGH_PRECISION_STEP_REL = 2.0 ** -18


def complex_mult_env() -> str:
    """The per-step complex-multiply base mode, read at *trace* time
    (so compiled executables must be keyed by it, like
    ``backends.lanemix_env``). **``gauss`` is the single tuned
    default** — everywhere: here, in ``bench.py``'s seeding, and as
    the :class:`KernelPolicy` base mode (the parity ladder pins it).
    Setting ``TNC_TPU_COMPLEX_MULT`` is a *forcing override* for A/B
    runs — it pins every step to one mode and disables the per-step
    promotion ladder (see :func:`plan_kernels`):

    - ``gauss`` (default): 3 real dots via the Gauss/Karatsuba identity —
      25% fewer MXU flops, but the pre-dot operand sums (ar+ai, bi-br,
      br+bi) are extra full-operand HBM passes AND mix magnitudes, so
      rounding error is relative to the *larger* mixed intermediate
      (the classic Karatsuba instability).
    - ``naive``: 4 real dots (rr-ii, ri+ir) — each dot's error is
      relative to its own product magnitude (the half-digit-tighter
      rung of the parity ladder, VERDICT r3 #2).
    - ``fused``: one Pallas kernel computing both outputs with each
      operand tile loaded once (:mod:`tnc_tpu.ops.pallas_complex`);
      naive-mode arithmetic, ~half the operand HBM traffic. Steps the
      kernel cannot take (non-cfirst orientation, ragged/small shapes)
      fall back to ``naive`` per step.
    - ``strassen``: one Strassen recursion level composed with the
      Gauss identity — 21 half-size real sub-GEMMs vs naive's 32
      half-units (:mod:`tnc_tpu.ops.strassen`) — on steps whose
      matricized shape clears the crossover; others run ``gauss``.
    - ``chain``: consecutive small steps grouped by
      :func:`tnc_tpu.ops.program.chain_groups` execute as ONE fused
      multi-step Pallas dispatch (naive arithmetic); ungrouped steps
      run ``gauss``.
    - ``auto``: the explicit spelling of the unforced default — the
      cost-model-driven promotion ladder.
    """
    return os.environ.get("TNC_TPU_COMPLEX_MULT", "gauss")


def complex_mult_forced() -> str | None:
    """The forcing override, or ``None`` when the env knob is unset
    (the promotion ladder decides per step). ``auto`` explicitly
    requests the ladder, so it is NOT a forced mode."""
    mode = os.environ.get("TNC_TPU_COMPLEX_MULT")
    if mode is None or mode == "auto":
        return None
    return mode


def complex_mult_key() -> str:
    """Trace-time *cache-key* form of the env knob: the forced mode, or
    ``auto`` when unset. Distinct from :func:`complex_mult_env` because
    an unset env lets the promotion ladder promote steps (prelude stem
    GEMMs → strassen), so it must NOT share compiled executables with
    an explicitly forced ``gauss``."""
    return os.environ.get("TNC_TPU_COMPLEX_MULT", "auto")


def dot_precision_forced() -> str | None:
    """The ``TNC_TPU_DOT_PRECISION`` forcing override (``high`` /
    ``highest``), or ``None`` when unset — the dot-precision analogue
    of :func:`complex_mult_forced`, the A/B knob for hardware
    campaigns. ``auto`` explicitly requests the per-step ladder
    (:func:`plan_precision_modes`), so it is NOT a forced mode. Read at
    *trace* time — every compiled-fn cache keys on
    :func:`dot_precision_key`."""
    mode = os.environ.get("TNC_TPU_DOT_PRECISION")
    if mode in (None, "", "auto"):
        return None
    if mode not in DOT_PRECISION_MODES:
        # an A/B knob must fail loudly: a typo ('hi') silently running
        # the highest rung would record mislabeled campaign data
        raise ValueError(
            f"TNC_TPU_DOT_PRECISION={mode!r}: expected one of "
            f"{DOT_PRECISION_MODES} or 'auto'"
        )
    return mode


def dot_precision_key() -> str:
    """Trace-time *cache-key* form of ``TNC_TPU_DOT_PRECISION``: the
    forced rung, or ``auto`` when unset — like
    :func:`complex_mult_key`, forced and auto traces must never share
    a compiled executable."""
    return os.environ.get("TNC_TPU_DOT_PRECISION", "auto")


def auto_step_mode(step) -> str | None:
    """Per-step promotion for executors outside a full
    :class:`KernelPolicy` plan (the hoisted prelude, whose stem GEMMs
    are exactly the Strassen regime): ``strassen`` when the step clears
    the crossover and no forcing override is set; ``None`` defers to
    the env default.

    Eligibility-gated only — unlike the full ladder this does NOT
    consult ``_strassen_saving_s``: the prelude executes inside traced
    functions whose caches key on the env, not on a fitted cost model,
    so a model-dependent decision here would silently serve stale
    traces as calibration evolves. On a device where Strassen loses,
    force ``TNC_TPU_COMPLEX_MULT=gauss`` (the A/B knob) to disable."""
    if complex_mult_forced() is not None:
        return None
    if _strassen_step_eligible(step):
        return "strassen"
    return None


def resolved_step_mode(step, mode: str | None = None) -> str:
    """The arithmetic :func:`apply_step_split` actually runs for a
    requested mode — the env/policy name folded through the per-step
    fallbacks (``strassen`` below the crossover → gauss; ``chain`` /
    ``auto`` outside a policy → gauss; unknown → gauss). The flop-
    crediting rule (:data:`EFFECTIVE_FLOP_FACTOR`) must be looked up
    on THIS name, never the raw request."""
    if mode is None:
        mode = complex_mult_env()
    if mode == "strassen":
        return "strassen" if _strassen_step_eligible(step) else "gauss"
    if mode == "fused_transpose":
        # the kernel's per-step gate falls back to the naive dots
        return (
            "fused_transpose"
            if fused_transpose_ineligible_reason(step) is None
            else "naive"
        )
    if mode in ("naive", "fused"):
        return mode
    return "gauss"


def split_array(array: np.ndarray, dtype: str = "float32") -> tuple[np.ndarray, np.ndarray]:
    """Complex array -> contiguous (real, imag) float pair.

    >>> import numpy as np
    >>> re, im = split_array(np.array([1 + 2j, 3 - 4j]))
    >>> re.tolist(), im.tolist()
    ([1.0, 3.0], [2.0, -4.0])
    >>> np.allclose(combine_array(re, im), [1 + 2j, 3 - 4j])
    True
    """
    array = np.asarray(array)
    return (
        np.ascontiguousarray(array.real, dtype=dtype),
        np.ascontiguousarray(array.imag, dtype=dtype),
    )


def combine_array(re: Any, im: Any) -> np.ndarray:
    return np.asarray(re) + 1j * np.asarray(im)


def _resolve_precision(precision):
    """Map the backend's precision knob to a lax.Precision (device only).

    On TPU, f32 dot_generals are emulated on the bf16 MXU: DEFAULT
    truncates to one bf16 pass (fast, ~2^-11 relative), HIGH runs the
    3-pass bf16x3 recomposition, HIGHEST the 6-pass bf16x6 (closest to
    true f32). The parity ladder 'default' < 'high' < 'float32' trades
    dot throughput against the BASELINE 1e-5 amplitude target; the
    campaign A/Bs pick the fastest level that still passes parity."""
    if precision in (None, "default"):
        return None
    from jax import lax

    if precision == "high":
        return lax.Precision.HIGH
    return lax.Precision.HIGHEST


def _resolve_step_precision(precision, precision_mode):
    """The ``lax.Precision`` one step's dots actually run at: the
    per-step :class:`KernelPolicy` rung when set (``high`` /
    ``highest``), else the ``TNC_TPU_DOT_PRECISION`` forcing override,
    else the backend-level ``precision`` knob — device path only (the
    host oracle's f64 matmuls take no precision)."""
    if not precision_mode:
        precision_mode = dot_precision_forced()
    if not precision_mode:
        return _resolve_precision(precision)
    return _resolve_precision(
        "high" if precision_mode == "high" else "float32"
    )


def gauss_matmul(xp, ar, ai, br, bi):
    """Complex matmul on split 2-D parts with 3 real matmuls (host path;
    device precision is handled by `_resolve_precision` + dot_general)."""
    k1 = xp.matmul(ar + ai, br)
    k2 = xp.matmul(ar, bi - br)
    k3 = xp.matmul(ai, br + bi)
    return k1 - k3, k1 + k2


def _as_kl(xp, part, dot_shape, cfirst):
    """Post-prep operand (shaped ``dot_shape``) → contract-dim-leading
    2-D ``(k, frees)`` matrix, the layout the Strassen/fused kernels
    share with the host oracle's ``as_km``."""
    if cfirst:
        return part.reshape(int(dot_shape[0]), -1)
    k = int(dot_shape[-1])
    flat = part.reshape(-1, k)
    return flat.T if xp is np else xp.swapaxes(flat, 0, 1)


def _strassen_step(xp, ar, ai, br, bi, step, precision):
    """One step through the gauss+strassen kernel: matricize both
    prepped operands to kl layout, fold ``swap``, run 21 half-size
    sub-GEMMs (:mod:`tnc_tpu.ops.strassen`)."""
    from tnc_tpu.ops.strassen import gauss_strassen_dot_kl

    a2r = _as_kl(xp, ar, step.a_dot, step.a_cfirst)
    a2i = _as_kl(xp, ai, step.a_dot, step.a_cfirst)
    b2r = _as_kl(xp, br, step.b_dot, step.b_cfirst)
    b2i = _as_kl(xp, bi, step.b_dot, step.b_cfirst)
    if step.swap:
        fr, fi, sr, si = b2r, b2i, a2r, a2i
    else:
        fr, fi, sr, si = a2r, a2i, b2r, b2i
    re, im = gauss_strassen_dot_kl(xp, fr, fi, sr, si, precision=precision)
    return re.reshape(step.out_store), im.reshape(step.out_store)


def apply_step_split(
    xp, apair, bpair, step, precision=None, mode=None, precision_mode=None
):
    """Split-complex analogue of ``backends.apply_step``: one pairwise
    contraction of (real, imag) pairs. The single source of truth
    shared by every split-mode executor. ``mode`` overrides the global
    env mode for this step — the :class:`KernelPolicy` hook; ``None``
    falls back to :func:`complex_mult_env` (``gauss``).
    ``precision_mode`` is the policy's per-step dot-precision rung
    (``high``/``highest``; empty defers to the
    ``TNC_TPU_DOT_PRECISION`` override, then the backend
    ``precision``)."""
    from tnc_tpu.ops.backends import _prep_operand

    if mode == "fused_transpose" and xp is not np:
        # the fused transpose-dot consumes the RAW stored views — it
        # must run BEFORE _prep_operand materializes the macro
        # transpose (that pass is exactly what it deletes); on
        # fallback the standard prep+naive path below takes over
        out = _try_fused_transpose_step(
            apair, bpair, step,
            _resolve_step_precision(precision, precision_mode),
        )
        if out is not None:
            return out
        mode = "naive"

    ar = _prep_operand(
        xp, apair[0], step.a_view, step.a_perm, step.a_dot, step.a_ops
    )
    ai = _prep_operand(
        xp, apair[1], step.a_view, step.a_perm, step.a_dot, step.a_ops
    )
    br = _prep_operand(
        xp, bpair[0], step.b_view, step.b_perm, step.b_dot, step.b_ops
    )
    bi = _prep_operand(
        xp, bpair[1], step.b_view, step.b_perm, step.b_dot, step.b_ops
    )
    if mode is None:
        mode = complex_mult_env()
    if mode == "strassen" and not _strassen_step_eligible(step):
        mode = "gauss"  # forced-strassen steps below the crossover
    if xp is np:
        if mode == "strassen":
            return _strassen_step(np, ar, ai, br, bi, step, None)

        def as_km(part, mat, cfirst):
            return part.reshape(mat) if cfirst else part.reshape(mat[::-1]).T

        ar = as_km(ar, step.a_mat, step.a_cfirst)
        ai = as_km(ai, step.a_mat, step.a_cfirst)
        br = as_km(br, step.b_mat, step.b_cfirst)
        bi = as_km(bi, step.b_mat, step.b_cfirst)
        if step.swap:
            ar, ai, br, bi = br.T, bi.T, ar, ai
        else:
            ar, ai = ar.T, ai.T
        if mode in ("naive", "fused", "fused_transpose"):
            # the fused kernels run naive arithmetic on host oracles
            re = ar @ br - ai @ bi
            im = ar @ bi + ai @ br
        else:
            re, im = gauss_matmul(np, ar, ai, br, bi)
        return re.reshape(step.out_store), im.reshape(step.out_store)

    import jax.numpy as jnp
    from jax import lax

    prec = _resolve_step_precision(precision, precision_mode)
    if mode == "strassen":
        return _strassen_step(jnp, ar, ai, br, bi, step, prec)
    ca = (0,) if step.a_cfirst else (len(step.a_dot) - 1,)
    cb = (0,) if step.b_cfirst else (len(step.b_dot) - 1,)

    def dot(x, y):
        if step.swap:
            return lax.dot_general(y, x, ((cb, ca), ((), ())), precision=prec)
        return lax.dot_general(x, y, ((ca, cb), ((), ())), precision=prec)

    if mode == "fused":
        out = _try_fused_step(ar, ai, br, bi, step, prec)
        if out is not None:
            return out
        mode = "naive"  # per-step fallback: same arithmetic
    if mode == "naive":
        re = dot(ar, br) - dot(ai, bi)
        im = dot(ar, bi) + dot(ai, br)
        return re.reshape(step.out_store), im.reshape(step.out_store)
    k1 = dot(ar + ai, br)
    k2 = dot(ar, bi - br)
    k3 = dot(ai, br + bi)
    return (k1 - k3).reshape(step.out_store), (k1 + k2).reshape(step.out_store)


def _strassen_step_eligible(step) -> bool:
    from tnc_tpu.ops.program import step_dims
    from tnc_tpu.ops.strassen import strassen_eligible

    m, k, n = step_dims(step)
    return strassen_eligible(m, k, n)


_FUSED_FALLBACK_WARNED: set[str] = set()


def _note_fused_fallback(reason: str, k: int, m: int, n: int, detail=""):
    """Count a per-step fused-kernel fallback with its reason (the
    ``ops.fused_fallback`` counter bench records pick up) and warn —
    once per reason per process; repeats go to debug so a small-step
    program doesn't spam a warning per step."""
    from tnc_tpu import obs

    obs.counter_add("ops.fused_fallback", reason=reason)
    msg = (
        "fused complex kernel fell back to naive dots for step "
        f"(K={k}, M={m}, N={n}): {reason}{': ' + detail if detail else ''}"
    )
    if reason in _FUSED_FALLBACK_WARNED:
        logger.debug(msg)
    else:
        _FUSED_FALLBACK_WARNED.add(reason)
        logger.warning(msg)


def _try_fused_step(ar, ai, br, bi, step, precision):
    """Route one step through the fused Pallas kernel when its layout
    allows (both operands contract-dim-leading, tileable shapes, big
    enough to amortize the grid); None means 'use the naive dots'.
    Every fallback is counted (``ops.fused_fallback``) with its
    eligibility reason — layout vs dtype vs tile/flop floor vs a
    kernel error — so bench records show *why* fused didn't fire.

    Caveat on failure surfaces: this runs at *trace* time under the
    executor's jit, so only trace-time errors can trigger the fallback
    (logged, not silent). A Mosaic lowering failure surfaces later when
    the enclosing jit compiles — the campaign's fused A/B stage is
    self-contained so such a failure costs one stage, not the window.
    """
    k = int(step.a_dot[0]) if step.a_cfirst else int(step.a_dot[-1])
    m = int(np.prod(step.a_dot, dtype=np.int64)) // max(k, 1)
    n = int(np.prod(step.b_dot, dtype=np.int64)) // max(k, 1)
    if step.swap:
        m, n = n, m
    if not (step.a_cfirst and step.b_cfirst):
        _note_fused_fallback("layout", k, m, n)
        return None
    from tnc_tpu.ops.pallas_complex import (
        fused_complex_dot_kl,
        ineligible_reason,
    )

    if str(ar.dtype) != "float32":
        _note_fused_fallback("dtype", k, m, n, str(ar.dtype))
        return None
    reason = ineligible_reason(k, m, n)
    if reason is not None:
        _note_fused_fallback(reason, k, m, n)
        return None
    import jax

    interpret = jax.default_backend() != "tpu"
    a2r, a2i = ar.reshape(k, -1), ai.reshape(k, -1)
    b2r, b2i = br.reshape(k, -1), bi.reshape(k, -1)
    try:
        if step.swap:
            re, im = fused_complex_dot_kl(
                b2r, b2i, a2r, a2i, interpret=interpret, precision=precision
            )
        else:
            re, im = fused_complex_dot_kl(
                a2r, a2i, b2r, b2i, interpret=interpret, precision=precision
            )
    except Exception as e:  # trace-time only; see docstring
        _note_fused_fallback("kernel_error", k, m, n, f"{type(e).__name__}: {e}")
        return None
    return re.reshape(step.out_store), im.reshape(step.out_store)


# -- fused transpose-matmul step glue -----------------------------------


_FUSED_TRANSPOSE_WARNED: set[str] = set()


def _note_fused_transpose_fallback(reason: str, k: int, m: int, n: int, detail=""):
    """Count a per-step fused-transpose fallback with its reason (the
    ``ops.fused_transpose_fallback`` counter bench's
    ``kernel_counters`` block picks up) and warn — once per reason per
    process, mirroring :func:`_note_fused_fallback`."""
    from tnc_tpu import obs

    obs.counter_add("ops.fused_transpose_fallback", reason=reason)
    msg = (
        "fused transpose-dot kernel fell back to prep+naive dots for "
        f"step (K={k}, M={m}, N={n}): {reason}"
        f"{': ' + detail if detail else ''}"
    )
    if reason in _FUSED_TRANSPOSE_WARNED:
        logger.debug(msg)
    else:
        _FUSED_TRANSPOSE_WARNED.add(reason)
        logger.warning(msg)


def _fused_transpose_layouts(step):
    """``(first, second)`` :class:`~tnc_tpu.ops.pallas_complex.
    OperandLayout` pair for one step with ``swap`` folded out (the
    first operand supplies the output rows), or ``None`` per side when
    the operand's layout cannot be described (staged-prep operands are
    rejected by the caller — their reshape/lanemix plans are baked for
    the flat buffer)."""
    from tnc_tpu.ops.pallas_complex import operand_layout

    a = operand_layout(step.a_view, step.a_perm, step.a_dot, step.a_cfirst)
    b = operand_layout(step.b_view, step.b_perm, step.b_dot, step.b_cfirst)
    return (b, a) if step.swap else (a, b)


def fused_transpose_ineligible_reason(step) -> str | None:
    """Why the fused transpose-dot cannot take one step — ``None``
    when it can (the static half of the gate; dtype and batch checks
    need live buffers and happen in :func:`_try_fused_transpose_step`).
    ``staged_prep`` rejects operands carrying a staged op plan: their
    minor-dim-safe reshape/lanemix sequence is the materialization the
    kernel would otherwise have to replicate per tile."""
    from tnc_tpu.ops.pallas_complex import transpose_dot_ineligible_reason
    from tnc_tpu.ops.program import step_dims

    if step.a_ops is not None or step.b_ops is not None:
        return "staged_prep"
    m, k, n = step_dims(step)
    first, second = _fused_transpose_layouts(step)
    return transpose_dot_ineligible_reason(first, second, k, m, n)


def fused_transpose_step_eligible(step) -> bool:
    """Can :func:`_try_fused_transpose_step` take this step?"""
    return fused_transpose_ineligible_reason(step) is None


def fused_transpose_runtime_ineligible_reason(apair, bpair, step) -> str | None:
    """The *runtime* half of the fused-transpose gate — conditions the
    static :func:`fused_transpose_ineligible_reason` cannot see because
    they need live buffers: non-f32 parts (``dtype``) and buffers
    carrying an extra leading batch axis (``batch`` — serving rebind
    threading cannot stream through the static block geometry). The
    ONE predicate shared by the kernel route
    (:func:`_try_fused_transpose_step`) and the span accounting
    (``backends.run_steps_timed``), so what the spans credit and what
    the kernel actually does can never diverge (``kernel_error`` stays
    the documented blind spot)."""
    ar, br = apair[0], bpair[0]
    if str(ar.dtype) != "float32" or str(br.dtype) != "float32":
        return "dtype"
    if ar.size != int(np.prod(step.a_view, dtype=np.int64)) or br.size != int(
        np.prod(step.b_view, dtype=np.int64)
    ):
        return "batch"
    return None


def _try_fused_transpose_step(apair, bpair, step, precision):
    """Route one step through the fused transpose-dot Pallas kernel
    (:func:`tnc_tpu.ops.pallas_complex.fused_transpose_dot_kl`) when
    its layout allows; ``None`` means 'run the standard prep + naive
    dots'. Takes the RAW stored (real, imag) pairs — the whole point
    is that the macro transpose is applied in the kernel's index maps,
    not materialized through HBM. Every fallback is counted
    (``ops.fused_transpose_fallback{reason=...}``). Same trace-time
    failure surface as :func:`_try_fused_step`."""
    from tnc_tpu.ops.program import step_dims

    m, k, n = step_dims(step)
    reason = fused_transpose_ineligible_reason(
        step
    ) or fused_transpose_runtime_ineligible_reason(apair, bpair, step)
    if reason is not None:
        _note_fused_transpose_fallback(reason, k, m, n)
        return None
    ar, ai = apair
    br, bi = bpair
    from tnc_tpu.ops.pallas_complex import fused_transpose_dot_kl

    first_lay, second_lay = _fused_transpose_layouts(step)
    a2 = (ar.reshape(step.a_view), ai.reshape(step.a_view))
    b2 = (br.reshape(step.b_view), bi.reshape(step.b_view))
    first, second = (b2, a2) if step.swap else (a2, b2)
    import jax

    interpret = jax.default_backend() != "tpu"
    try:
        re, im = fused_transpose_dot_kl(
            first[0], first[1], second[0], second[1],
            first_lay, second_lay,
            interpret=interpret, precision=precision,
        )
    except Exception as e:  # trace-time only; see _try_fused_step
        _note_fused_transpose_fallback(
            "kernel_error", k, m, n, f"{type(e).__name__}: {e}"
        )
        return None
    return re.reshape(step.out_store), im.reshape(step.out_store)


# -- kernel promotion ladder --------------------------------------------


@dataclass(frozen=True)
class KernelPolicy:
    """Per-step kernel choice for one compiled program.

    ``modes[i]`` is the lowering of step ``i`` (``naive`` / ``gauss`` /
    ``fused`` / ``fused_transpose`` / ``strassen``); ``chains`` are
    ``(start, end)`` step spans that execute as ONE fused multi-step
    Pallas dispatch
    (:func:`tnc_tpu.ops.pallas_complex.fused_chain_kl`). Chained steps
    carry mode ``naive`` — the chain kernel's arithmetic — so the host
    oracle and the per-step device fallback compute the identical
    sequence. ``precision_modes[i]`` is step ``i``'s dot-precision
    rung (``highest`` / ``high`` = bf16x3; empty string defers to the
    ``TNC_TPU_DOT_PRECISION`` override, then the backend precision);
    the empty tuple means no step carries a rung. A policy is part of
    the jit cache key (:func:`tnc_tpu.ops.backends.jit_program`): two
    policies over the same program — including two that differ ONLY in
    precision rungs — are different executables.
    """

    modes: tuple[str, ...]
    chains: tuple[tuple[int, int], ...] = ()
    precision_modes: tuple[str, ...] = ()

    def signature(self) -> tuple:
        return (self.modes, self.chains, self.precision_modes)

    def precision_mode(self, i: int) -> str:
        """Step ``i``'s dot-precision rung ('' = defer)."""
        return self.precision_modes[i] if self.precision_modes else ""

    def chained_steps(self) -> set[int]:
        return {i for s, e in self.chains for i in range(s, e)}

    def dispatch_count(self) -> int:
        """Device dispatches this policy issues: one per unchained step,
        one per chain."""
        return len(self.modes) - len(self.chained_steps()) + len(self.chains)


def _chain_pays(cost_model, steps) -> bool:
    """Is fusing this run of steps into one dispatch a predicted win?
    Saves ``len(steps) - 1`` dispatch overheads; costs the naive-vs-
    gauss flop difference (the chain kernel runs 4 dots where the
    default ladder would run 3). With no fitted model the grouping
    pass's own size bound (steps under the fused kernel's flop floor)
    already selects dispatch-dominated steps — accept."""
    if cost_model is None:
        return True
    from tnc_tpu.ops.program import step_flops

    flops = sum(step_flops(st) for st in steps)
    # complex k*m*n units → real-multiply units: naive 8x, gauss 6x,
    # so fusing costs 2 extra units per k*m*n; each saved dispatch is
    # worth its flop-equivalent under the fitted model
    extra_flops = 2.0 * flops
    saved_flops = (
        len(steps) - 1
    ) * cost_model.dispatch_equivalent_flops()
    return saved_flops > extra_flops


def _strassen_saving_s(cost_model, m: int, k: int, n: int) -> float:
    """Predicted seconds one Strassen level saves over gauss on an
    eligible step (negative = loses): the saved multiplies (0.75 →
    21/32 of naive) against the 15 extra quadrant-sized elementwise
    passes per real GEMM (bandwidth). With no fitted model the margin
    is ``+inf`` — eligibility alone decides, the pre-calibration
    behavior."""
    if cost_model is None:
        return float("inf")
    from tnc_tpu.ops.strassen import GAUSS_STRASSEN_FLOP_FACTOR

    naive_real_flops = 8.0 * m * k * n
    saved_s = (
        0.75 - GAUSS_STRASSEN_FLOP_FACTOR
    ) * naive_real_flops / cost_model.flops_per_s
    if not cost_model.bytes_per_s:
        return saved_s
    # ~15 add/sub passes over (m/2, k/2)+(k/2, n/2) quadrants, 3 Gauss
    # products, f32 in + out
    quad_bytes = 4.0 * ((m * k + k * n) / 4.0) * 2.0
    extra_s = 3.0 * 15.0 * quad_bytes / cost_model.bytes_per_s
    return saved_s - extra_s


def _fused_transpose_saving_s(cost_model, step) -> float:
    """Predicted seconds the fused transpose-dot saves over the
    default prep+gauss path on one eligible step (negative = loses):
    the deleted materialized-transpose HBM pass (read + write of every
    permuted operand's (real, imag) pair — :func:`tnc_tpu.ops.program.
    step_prep_elems`) against the naive-vs-gauss flop difference (the
    kernel runs 4 dots where gauss runs 3). Unlike Strassen, a missing
    model means NO promotion (``-inf``): the rung's entire case is
    bandwidth, so without a fitted bandwidth term there is no evidence
    it pays — the ``TNC_TPU_COMPLEX_MULT=fused_transpose`` override is
    the A/B path."""
    if cost_model is None or not cost_model.bytes_per_s:
        return float("-inf")
    from tnc_tpu.ops.program import step_flops, step_prep_elems

    prep = step_prep_elems(step)
    if prep <= 0.0:
        return float("-inf")  # no transpose pass to save
    # f32 split pairs: 8 bytes per complex element, the device width
    saved_s = prep * 8.0 / cost_model.bytes_per_s
    # naive 8 vs gauss 6 real-multiply units per k*m*n (same convention
    # as _chain_pays); the fitted flops_per_s is per k*m*n unit
    extra_s = 2.0 * step_flops(step) / cost_model.flops_per_s
    return saved_s - extra_s


def chain_flop_ceiling(cost_model) -> float:
    """Chain-candidate step-size ceiling in the fused kernel's
    ``2*k*m*n`` units, priced in calibrated seconds: a step is worth
    chaining while its compute time is within ~one dispatch overhead
    (:meth:`~tnc_tpu.obs.calibrate.CalibratedCostModel.
    dispatch_equivalent_flops`), so the ceiling rises above the static
    ``MIN_FLOPS`` small-step bucket exactly when the fitted overhead
    says bigger steps are still dispatch-bound — PR 6's chain fusion
    extended upward. Never *below* ``MIN_FLOPS``: the static bound is
    the no-model floor."""
    from tnc_tpu.ops.pallas_complex import MIN_FLOPS

    if cost_model is None:
        return float(MIN_FLOPS)
    return max(float(MIN_FLOPS), 2.0 * cost_model.dispatch_equivalent_flops())


def plan_precision_modes(
    steps,
    cost_model=None,
    force: str | None = None,
    parity_budget: float = 1e-5,
) -> tuple[str, ...]:
    """Per-step dot-precision rungs for :func:`plan_kernel_steps`.

    ``force`` (default: the ``TNC_TPU_DOT_PRECISION`` override via
    :func:`dot_precision_forced`) pins every step for A/B runs.
    Unforced, the ladder promotes a step to ``high`` (bf16x3, ≈2× dot
    throughput) only when ALL of:

    - a fitted cost model with a bandwidth term exists and predicts the
      step *compute*-dominated (flop time > byte time) — elsewhere the
      dots aren't the bottleneck and the rung buys nothing;
    - the step is in the ``stem`` bucket — the big square-ish GEMMs
      whose products dominate the amplitude, where
      ``scripts/precision_parity_smoke.py`` pins the bf16x3 rung's
      measured relative error;
    - the ``parity_budget`` (the run's amplitude-parity target, 1e-5
      by default) clears the documented bf16x3 rung
      (:data:`HIGH_PRECISION_STEP_REL`, ~3.8e-6) with 2× headroom —
      a tight-budget run never trades parity for speed.

    Returns ``()`` (no rungs) when nothing promotes, so unpromoted
    policies keep their pre-ladder signatures.
    """
    steps = tuple(steps)
    if force is None:
        force = dot_precision_forced()
    if force is not None:
        return (force,) * len(steps)
    if cost_model is None or not cost_model.bytes_per_s:
        return ()
    if parity_budget < 2.0 * HIGH_PRECISION_STEP_REL:
        return ()
    from tnc_tpu.ops.program import step_elems, step_flops

    out = []
    for st in steps:
        promote = False
        if step_bucket(st) == "stem":
            flop_s = step_flops(st) / cost_model.flops_per_s
            elems_in, elems_out = step_elems(st)
            byte_s = (elems_in + elems_out) * 8.0 / cost_model.bytes_per_s
            promote = flop_s > byte_s
        out.append("high" if promote else "")
    if not any(out):
        return ()
    return tuple(out)


def plan_kernels(
    program: ContractionProgram,
    cost_model=None,
    force: str | None = None,
    chain_max_flops: float | None = None,
) -> KernelPolicy:
    """Build the kernel promotion ladder for one program — the
    per-step decision that replaced the global env mode. Thin wrapper
    over :func:`plan_kernel_steps` (the chunked executor plans per
    chunk-subsequence with the same rules).

    ``force`` (default: the ``TNC_TPU_COMPLEX_MULT`` override via
    :func:`complex_mult_forced`) pins the decision for A/B runs:
    ``naive``/``gauss``/``fused``/``fused_transpose`` uniformly
    (the fused rungs fall back per step at trace time, counted);
    ``strassen`` promotes every step over the crossover (others run
    gauss); ``chain`` fuses every groupable run (others run gauss).
    The per-step dot-precision rung is planned alongside
    (:func:`plan_precision_modes` — ``TNC_TPU_DOT_PRECISION`` forces
    it independently of the mode override). Unforced, the ladder is
    cost-model-driven (``cost_model``: a
    :class:`tnc_tpu.obs.calibrate.CalibratedCostModel` or None):

    - runs of consecutive steps under the calibrated chain ceiling
      (:func:`chain_flop_ceiling` — ``MIN_FLOPS`` statically, rising
      with the fitted ``dispatch_overhead_s``) whose fusion saves more
      dispatch overhead than the naive-vs-gauss flop difference costs
      → one fused **chain** dispatch;
    - transpose-carrying steps the fused transpose-dot can stream
      where the deleted HBM transpose pass beats the extra naive dot
      (:func:`_fused_transpose_saving_s` — needs a fitted bandwidth
      term) → **fused_transpose**;
    - steps whose matricized shape clears the Strassen crossover
      (square-ish, ≥2^11 per dim) where the multiply saving beats the
      extra passes → **strassen** (when both rungs pay, the larger
      predicted saving wins);
    - everything else → **gauss**, the tuned default;
    - stem-bucket compute-dominated steps additionally promote their
      dots to the bf16x3 ``high`` rung under the parity budget.
    """
    return plan_kernel_steps(
        program.steps, cost_model, force, chain_max_flops
    )


def plan_kernel_steps(
    steps,
    cost_model=None,
    force: str | None = None,
    chain_max_flops: float | None = None,
    precision_force: str | None = None,
    parity_budget: float = 1e-5,
) -> KernelPolicy:
    """:func:`plan_kernels` over a bare step sequence — chain spans and
    modes are indexed relative to ``steps[0]``."""
    from tnc_tpu.ops.program import chain_groups, step_dims
    from tnc_tpu.ops.strassen import strassen_eligible

    steps = tuple(steps)
    n = len(steps)
    if force is None:
        force = complex_mult_forced()
    pmodes = plan_precision_modes(
        steps, cost_model, precision_force, parity_budget
    )
    if force in ("naive", "gauss", "fused", "fused_transpose"):
        return KernelPolicy((force,) * n, (), pmodes)
    if force == "strassen":
        modes = tuple(
            "strassen" if _strassen_step_eligible(st) else "gauss"
            for st in steps
        )
        if pmodes and dot_precision_forced() is None and precision_force is None:
            # see the auto branch below: no auto bf16x3 on strassen
            pmodes = tuple(
                "" if modes[i] == "strassen" else p
                for i, p in enumerate(pmodes)
            )
            if not any(pmodes):
                pmodes = ()
        return KernelPolicy(modes, (), pmodes)

    if chain_max_flops is None and force != "chain":
        chain_max_flops = chain_flop_ceiling(cost_model)
    chains = chain_groups(steps, max_flops=chain_max_flops)
    if force != "chain":  # auto: keep only the chains the model likes
        chains = tuple(
            (s, e) for s, e in chains if _chain_pays(cost_model, steps[s:e])
        )
    chained = {i for s, e in chains for i in range(s, e)}
    modes = []
    for i, st in enumerate(steps):
        if i in chained:
            modes.append("naive")  # the chain kernel's arithmetic
            continue
        if force == "chain":
            modes.append("gauss")
            continue
        m, k, nn = step_dims(st)
        strassen_gain = (
            _strassen_saving_s(cost_model, m, k, nn)
            if strassen_eligible(m, k, nn)
            else float("-inf")
        )
        transpose_gain = (
            _fused_transpose_saving_s(cost_model, st)
            if fused_transpose_step_eligible(st)
            else float("-inf")
        )
        if strassen_gain <= 0.0 and transpose_gain <= 0.0:
            modes.append("gauss")
        elif strassen_gain >= transpose_gain:
            modes.append("strassen")
        else:
            modes.append("fused_transpose")
    if pmodes and dot_precision_forced() is None and precision_force is None:
        # never STACK the auto bf16x3 rung on a Strassen step: the
        # budget check models the plain-dot rung only, and Strassen's
        # extra add/sub passes amplify the error past both documented
        # rungs. A forced TNC_TPU_DOT_PRECISION is the explicit A/B —
        # it stays global (its parity oracle is the gate).
        pmodes = tuple(
            "" if modes[i] == "strassen" else p
            for i, p in enumerate(pmodes)
        )
        if not any(pmodes):
            pmodes = ()
    return KernelPolicy(tuple(modes), chains, pmodes)


def step_bucket(step) -> str:
    """Shape bucket of one step for MFU reporting — policy-independent
    so buckets stay comparable across runs: ``stem`` (clears the
    Strassen crossover), ``small`` (under the fused kernel's flop
    floor, the dispatch-dominated regime), ``medium`` (the rest)."""
    from tnc_tpu.ops.pallas_complex import MIN_FLOPS
    from tnc_tpu.ops.program import step_dims, step_flops
    from tnc_tpu.ops.strassen import strassen_eligible

    m, k, n = step_dims(step)
    if strassen_eligible(m, k, n):
        return "stem"
    if 2 * step_flops(step) < MIN_FLOPS:
        return "small"
    return "medium"


def effective_step_flops(step, mode: str) -> float:
    """A step's flop count credited for the kernel mode that ran it
    (same ``k*m*n`` complex units as :func:`tnc_tpu.ops.program.
    step_flops`, scaled by :data:`EFFECTIVE_FLOP_FACTOR`) — the number
    MFU should divide by so algorithmically-cheaper kernels don't
    inflate it."""
    from tnc_tpu.ops.program import step_flops

    return step_flops(step) * EFFECTIVE_FLOP_FACTOR.get(mode, 1.0)


def kernel_plan_summary(
    program: ContractionProgram,
    policy: KernelPolicy | None = None,
    dtype_bytes: float = 8.0,
) -> dict:
    """JSON-able per-bucket summary of a program under a policy: step
    counts, naive vs effective (mode-credited) flops, the mode and
    dot-precision mixes, predicted HBM bytes under the naive prep+dot
    path vs under the planned modes (the fused transpose rung's
    deleted pass shows up as ``pred_bytes_planned <
    pred_bytes_naive`` on transpose-carrying buckets — the invariant
    ``scripts/perf_gate.py`` enforces), and the dispatch count
    (chains collapse to one). ``dtype_bytes`` defaults to the device
    path's f32 split-pair width (8 B per complex element). The static
    side of ``bench.py``'s per-bucket MFU report."""
    if policy is None:
        policy = plan_kernels(program)
    from tnc_tpu.ops.program import step_elems, step_flops, step_prep_elems

    buckets: dict[str, dict] = {}
    for i, st in enumerate(program.steps):
        b = buckets.setdefault(
            step_bucket(st),
            {
                "steps": 0,
                "flops": 0.0,
                "effective_flops": 0.0,
                "modes": {},
                "precision": {},
                "transpose_steps": 0,
                "pred_bytes_naive": 0.0,
                "pred_bytes_planned": 0.0,
            },
        )
        mode = policy.modes[i]
        resolved = resolved_step_mode(st, mode)
        b["steps"] += 1
        b["flops"] += step_flops(st)
        b["effective_flops"] += effective_step_flops(st, resolved)
        b["modes"][mode] = b["modes"].get(mode, 0) + 1
        rung = policy.precision_mode(i) or "default"
        b["precision"][rung] = b["precision"].get(rung, 0) + 1
        if step_prep_elems(st) > 0.0:
            b["transpose_steps"] += 1
        naive_in, naive_out = step_elems(st)
        plan_in, plan_out = step_elems(st, mode=resolved)
        b["pred_bytes_naive"] += (naive_in + naive_out) * dtype_bytes
        b["pred_bytes_planned"] += (plan_in + plan_out) * dtype_bytes
    for b in buckets.values():
        b["flops"] = float(f"{b['flops']:.4e}")
        b["effective_flops"] = float(f"{b['effective_flops']:.4e}")
        b["pred_bytes_naive"] = float(f"{b['pred_bytes_naive']:.4e}")
        b["pred_bytes_planned"] = float(f"{b['pred_bytes_planned']:.4e}")
        if b["steps"]:
            b["pred_bytes_per_step_naive"] = float(
                f"{b['pred_bytes_naive'] / b['steps']:.4e}"
            )
            b["pred_bytes_per_step_planned"] = float(
                f"{b['pred_bytes_planned'] / b['steps']:.4e}"
            )
    return {
        "buckets": buckets,
        "dispatches": policy.dispatch_count(),
        "chains": len(policy.chains),
        "chained_steps": len(policy.chained_steps()),
    }


def _run_chain_split(steps, buffers, precision, precision_mode=""):
    """Execute a grouped run of steps as ONE fused Pallas dispatch.

    Non-carried operands are prepped to contract-dim-leading 2-D
    outside the kernel (XLA-land, where transposes are free to fuse);
    the carried value flows through the kernel in VMEM. Returns the
    final (re, im) pair reshaped to the last step's ``out_store``.
    Raises on any trace-time problem — the caller falls back to the
    sequential naive loop (same arithmetic)."""
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import _prep_operand
    from tnc_tpu.ops.pallas_complex import ChainLink, fused_chain_kl

    prec = _resolve_step_precision(precision, precision_mode)
    interpret = jax.default_backend() != "tpu"

    def prep_kl(pair, view, perm, dot_shape, ops, cfirst):
        r = _prep_operand(jnp, pair[0], view, perm, dot_shape, ops)
        i = _prep_operand(jnp, pair[1], view, perm, dot_shape, ops)
        return _as_kl(jnp, r, dot_shape, cfirst), _as_kl(
            jnp, i, dot_shape, cfirst
        )

    head = steps[0]
    a = prep_kl(
        buffers[head.lhs], head.a_view, head.a_perm, head.a_dot,
        head.a_ops, head.a_cfirst,
    )
    b = prep_kl(
        buffers[head.rhs], head.b_view, head.b_perm, head.b_dot,
        head.b_ops, head.b_cfirst,
    )
    first, second = (b, a) if head.swap else (a, b)
    first_ops = (first[0], first[1], second[0], second[1])

    link_ops = []
    links = []
    run_slot = head.lhs
    for st in steps[1:]:
        carried_a = st.lhs == run_slot
        if carried_a:
            c_pair, c_view, c_perm, c_dot, c_ops, c_cfirst = (
                buffers[st.rhs], st.b_view, st.b_perm, st.b_dot,
                st.b_ops, st.b_cfirst,
            )
            carried_dot, carried_cfirst = st.a_dot, st.a_cfirst
        else:
            c_pair, c_view, c_perm, c_dot, c_ops, c_cfirst = (
                buffers[st.lhs], st.a_view, st.a_perm, st.a_dot,
                st.a_ops, st.a_cfirst,
            )
            carried_dot, carried_cfirst = st.b_dot, st.b_cfirst
        link_ops.append(
            prep_kl(c_pair, c_view, c_perm, c_dot, c_ops, c_cfirst)
        )
        k = int(carried_dot[0]) if carried_cfirst else int(carried_dot[-1])
        f = int(math.prod(carried_dot)) // max(k, 1)
        carried_shape = (k, f) if carried_cfirst else (f, k)
        k_axis = 0 if carried_cfirst else 1
        carried_first = (not carried_a) if st.swap else carried_a
        links.append(ChainLink(carried_first, carried_shape, k_axis))
        run_slot = st.lhs

    re, im = fused_chain_kl(
        first_ops, link_ops, links, interpret=interpret, precision=prec
    )
    out_store = steps[-1].out_store
    return re.reshape(out_store), im.reshape(out_store)


def run_chain_split(xp, steps, buffers, precision=None, precision_mode=""):
    """Execute one chain group with full buffer bookkeeping — the
    fused dispatch on device, the sequential naive loop on the host
    oracle (bit-identical arithmetic) or when the kernel can't trace
    (counted as ``ops.fused_chain_fallback``). ``precision_mode`` is
    the chain's dot-precision rung (one rung per chain — the policy's
    head-step entry). Mutates ``buffers`` the same way the sequential
    loop would."""
    from tnc_tpu import obs

    out = None
    if xp is not np:
        try:
            out = _run_chain_split(steps, buffers, precision, precision_mode)
        except Exception as e:  # trace-time only — same contract as fused
            obs.counter_add("ops.fused_chain_fallback")
            logger.warning(
                "fused chain kernel fell back to the sequential loop "
                "(%d steps): %s: %s", len(steps), type(e).__name__, e,
            )
            out = None
    if out is None:
        for st in steps:
            buffers[st.lhs] = apply_step_split(
                xp, buffers[st.lhs], buffers[st.rhs], st, precision,
                mode="naive", precision_mode=precision_mode,
            )
            buffers[st.rhs] = None
        return buffers[steps[-1].lhs]
    for st in steps:
        buffers[st.rhs] = None
    buffers[steps[-1].lhs] = out
    return out


def run_steps_split(
    xp,
    program: ContractionProgram,
    buffers: list[tuple[Any, Any] | None],
    precision=None,
    policy: KernelPolicy | None = None,
):
    """Split-complex analogue of ``backends._run_steps``; ``buffers`` are
    (real, imag) pairs and the result is a pair in **stored** shape
    (callers reshape to ``result_shape`` on the host). ``policy`` (a
    :class:`KernelPolicy`) promotes steps per the kernel ladder; None
    runs every step under the env mode (``gauss`` default)."""
    steps = program.steps
    chain_end = (
        {s: e for s, e in policy.chains} if policy is not None else {}
    )
    i = 0
    while i < len(steps):
        end = chain_end.get(i)
        if end is not None:
            run_chain_split(
                xp, steps[i:end], buffers, precision,
                precision_mode=policy.precision_mode(i),
            )
            i = end
            continue
        step = steps[i]
        buffers[step.lhs] = apply_step_split(
            xp, buffers[step.lhs], buffers[step.rhs], step, precision,
            mode=policy.modes[i] if policy is not None else None,
            precision_mode=(
                policy.precision_mode(i) if policy is not None else None
            ),
        )
        buffers[step.rhs] = None
        i += 1
    return buffers[program.result_slot]
