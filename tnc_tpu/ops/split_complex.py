"""Split-complex execution: complex tensors as (real, imag) float pairs.

The TPU's MXU is a real-arithmetic systolic array, and this stack exposes
no complex dtypes at all — so the TPU path represents every tensor as two
float32 arrays and lowers each pairwise contraction to **three** real
matmuls via the Gauss/Karatsuba identity (25% fewer flops than the naive
four):

    k1 = (ar + ai) @ br
    k2 = ar @ (bi - br)
    k3 = ai @ (br + bi)
    real = k1 - k3,  imag = k1 + k2

This is the "split real/imag representation" contingency the survey
flagged for TPU complex support (SURVEY.md §7 hard parts), promoted to
the primary device layout. Host-side data stays complex128; the split
happens at the host→device boundary.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

from tnc_tpu.ops.program import ContractionProgram


def complex_mult_env() -> str:
    """Complex-multiply lowering, read at *trace* time (so compiled
    executables must be keyed by it, like ``backends.lanemix_env``):

    - ``gauss`` (default): 3 real dots via the Gauss/Karatsuba identity —
      25% fewer MXU flops, but the pre-dot operand sums (ar+ai, bi-br,
      br+bi) are extra full-operand HBM passes AND mix magnitudes, so
      rounding error is relative to the *larger* mixed intermediate
      (the classic Karatsuba instability).
    - ``naive``: 4 real dots (rr-ii, ri+ir) — each dot's error is
      relative to its own product magnitude; measured the difference is
      the missing half-digit to the 1e-5 parity target at f32
      (VERDICT r3 #2).
    - ``fused``: one Pallas kernel computing both outputs with each
      operand tile loaded once (:mod:`tnc_tpu.ops.pallas_complex`);
      naive-mode arithmetic, ~half the operand HBM traffic. Steps the
      kernel cannot take (non-cfirst orientation, ragged/small shapes)
      fall back to ``naive`` per step.
    """
    return os.environ.get("TNC_TPU_COMPLEX_MULT", "gauss")


def split_array(array: np.ndarray, dtype: str = "float32") -> tuple[np.ndarray, np.ndarray]:
    """Complex array -> contiguous (real, imag) float pair.

    >>> import numpy as np
    >>> re, im = split_array(np.array([1 + 2j, 3 - 4j]))
    >>> re.tolist(), im.tolist()
    ([1.0, 3.0], [2.0, -4.0])
    >>> np.allclose(combine_array(re, im), [1 + 2j, 3 - 4j])
    True
    """
    array = np.asarray(array)
    return (
        np.ascontiguousarray(array.real, dtype=dtype),
        np.ascontiguousarray(array.imag, dtype=dtype),
    )


def combine_array(re: Any, im: Any) -> np.ndarray:
    return np.asarray(re) + 1j * np.asarray(im)


def _resolve_precision(precision):
    """Map the backend's precision knob to a lax.Precision (device only).

    On TPU, f32 dot_generals are emulated on the bf16 MXU: DEFAULT
    truncates to one bf16 pass (fast, ~2^-11 relative), HIGH runs the
    3-pass bf16x3 recomposition, HIGHEST the 6-pass bf16x6 (closest to
    true f32). The parity ladder 'default' < 'high' < 'float32' trades
    dot throughput against the BASELINE 1e-5 amplitude target; the
    campaign A/Bs pick the fastest level that still passes parity."""
    if precision in (None, "default"):
        return None
    from jax import lax

    if precision == "high":
        return lax.Precision.HIGH
    return lax.Precision.HIGHEST


def gauss_matmul(xp, ar, ai, br, bi):
    """Complex matmul on split 2-D parts with 3 real matmuls (host path;
    device precision is handled by `_resolve_precision` + dot_general)."""
    k1 = xp.matmul(ar + ai, br)
    k2 = xp.matmul(ar, bi - br)
    k3 = xp.matmul(ai, br + bi)
    return k1 - k3, k1 + k2


def apply_step_split(xp, apair, bpair, step, precision=None):
    """Split-complex analogue of ``backends.apply_step``: one pairwise
    contraction of (real, imag) pairs via three real dots (Gauss). The
    single source of truth shared by every split-mode executor."""
    from tnc_tpu.ops.backends import _prep_operand

    ar = _prep_operand(
        xp, apair[0], step.a_view, step.a_perm, step.a_dot, step.a_ops
    )
    ai = _prep_operand(
        xp, apair[1], step.a_view, step.a_perm, step.a_dot, step.a_ops
    )
    br = _prep_operand(
        xp, bpair[0], step.b_view, step.b_perm, step.b_dot, step.b_ops
    )
    bi = _prep_operand(
        xp, bpair[1], step.b_view, step.b_perm, step.b_dot, step.b_ops
    )
    mode = complex_mult_env()
    if xp is np:

        def as_km(part, mat, cfirst):
            return part.reshape(mat) if cfirst else part.reshape(mat[::-1]).T

        ar = as_km(ar, step.a_mat, step.a_cfirst)
        ai = as_km(ai, step.a_mat, step.a_cfirst)
        br = as_km(br, step.b_mat, step.b_cfirst)
        bi = as_km(bi, step.b_mat, step.b_cfirst)
        if step.swap:
            ar, ai, br, bi = br.T, bi.T, ar, ai
        else:
            ar, ai = ar.T, ai.T
        if mode in ("naive", "fused"):  # fused is naive arithmetic on host
            re = ar @ br - ai @ bi
            im = ar @ bi + ai @ br
        else:
            re, im = gauss_matmul(np, ar, ai, br, bi)
        return re.reshape(step.out_store), im.reshape(step.out_store)

    from jax import lax

    prec = _resolve_precision(precision)
    ca = (0,) if step.a_cfirst else (len(step.a_dot) - 1,)
    cb = (0,) if step.b_cfirst else (len(step.b_dot) - 1,)

    def dot(x, y):
        if step.swap:
            return lax.dot_general(y, x, ((cb, ca), ((), ())), precision=prec)
        return lax.dot_general(x, y, ((ca, cb), ((), ())), precision=prec)

    if mode == "fused":
        out = _try_fused_step(ar, ai, br, bi, step, prec)
        if out is not None:
            return out
        mode = "naive"  # per-step fallback: same arithmetic
    if mode == "naive":
        re = dot(ar, br) - dot(ai, bi)
        im = dot(ar, bi) + dot(ai, br)
        return re.reshape(step.out_store), im.reshape(step.out_store)
    k1 = dot(ar + ai, br)
    k2 = dot(ar, bi - br)
    k3 = dot(ai, br + bi)
    return (k1 - k3).reshape(step.out_store), (k1 + k2).reshape(step.out_store)


def _try_fused_step(ar, ai, br, bi, step, precision):
    """Route one step through the fused Pallas kernel when its layout
    allows (both operands contract-dim-leading, tileable shapes, big
    enough to amortize the grid); None means 'use the naive dots'.

    Caveat on failure surfaces: this runs at *trace* time under the
    executor's jit, so only trace-time errors can trigger the fallback
    (logged, not silent). A Mosaic lowering failure surfaces later when
    the enclosing jit compiles — the campaign's fused A/B stage is
    self-contained so such a failure costs one stage, not the window.
    """
    if not (step.a_cfirst and step.b_cfirst):
        return None
    from tnc_tpu.ops.pallas_complex import eligible, fused_complex_dot_kl

    k = int(step.a_dot[0])
    m = int(np.prod(step.a_dot[1:], dtype=np.int64)) if len(step.a_dot) > 1 else 1
    n = int(np.prod(step.b_dot[1:], dtype=np.int64)) if len(step.b_dot) > 1 else 1
    if step.swap:
        m, n = n, m
    if not eligible(k, m, n):
        return None
    import jax

    interpret = jax.default_backend() != "tpu"
    a2r, a2i = ar.reshape(k, -1), ai.reshape(k, -1)
    b2r, b2i = br.reshape(k, -1), bi.reshape(k, -1)
    try:
        if step.swap:
            re, im = fused_complex_dot_kl(
                b2r, b2i, a2r, a2i, interpret=interpret, precision=precision
            )
        else:
            re, im = fused_complex_dot_kl(
                a2r, a2i, b2r, b2i, interpret=interpret, precision=precision
            )
    except Exception as e:  # trace-time only; see docstring
        import logging

        logging.getLogger(__name__).warning(
            "fused complex kernel fell back to naive dots for step "
            "(K=%d, M=%d, N=%d): %s: %s", k, m, n, type(e).__name__, e,
        )
        return None
    return re.reshape(step.out_store), im.reshape(step.out_store)


def run_steps_split(
    xp,
    program: ContractionProgram,
    buffers: list[tuple[Any, Any] | None],
    precision=None,
):
    """Split-complex analogue of ``backends._run_steps``; ``buffers`` are
    (real, imag) pairs and the result is a pair in **stored** shape
    (callers reshape to ``result_shape`` on the host)."""
    for step in program.steps:
        buffers[step.lhs] = apply_step_split(
            xp, buffers[step.lhs], buffers[step.rhs], step, precision
        )
        buffers[step.rhs] = None
    return buffers[program.result_slot]
