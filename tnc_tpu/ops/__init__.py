from tnc_tpu.ops.program import ContractionProgram, PairStep, build_program  # noqa: F401
from tnc_tpu.ops.backends import (  # noqa: F401
    Backend,
    JaxBackend,
    NumpyBackend,
    get_backend,
)
from tnc_tpu.ops.hoist import (  # noqa: F401
    HoistedProgram,
    hoist_sliced_program,
)
