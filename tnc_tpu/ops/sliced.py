"""Sliced contraction execution.

A :class:`SlicedProgram` pairs a reduced-metadata
:class:`~tnc_tpu.ops.program.ContractionProgram` (sliced legs removed)
with indexing instructions describing, for each input, which axes are
fixed per slice. Execution sums the program's result over all slice index
combinations.

TPU mapping: all slices share one compiled program; the JAX backend runs
the *entire* slice loop on device as a ``lax.fori_loop`` whose body
indexes the (resident-in-HBM) full inputs, runs the contraction steps,
and accumulates — no host round-trips between slices.

Slice-invariant stem hoisting (``hoist=True``): steps whose operands
depend on no sliced leg are bit-identical across slices. The hoist pass
(:mod:`tnc_tpu.ops.hoist`) splits the program into an invariant
**prelude** executed once and a per-slice **residual** program whose
extra input slots are the prelude's cached intermediates; on device the
prelude runs before the ``fori_loop``/``scan`` and its outputs stay
resident in HBM as loop constants. Execution cost drops from
``num_slices * total_flops`` to ``invariant_flops + num_slices *
residual_flops``; the slicing planner scores candidate slice sets with
the same formula (:mod:`tnc_tpu.contractionpath.slicing`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.slicing import Slicing
from tnc_tpu.ops.program import (
    ContractionProgram,
    build_program,
    steps_bytes,
    steps_flops,
)
from tnc_tpu.ops.backends import _run_steps, run_steps_timed
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


@dataclass(frozen=True)
class SlicedProgram:
    program: ContractionProgram  # over slice-reduced shapes
    slicing: Slicing
    # per input slot: ((axis_in_original_tensor, slice_position), ...)
    # ordered by axis, where slice_position indexes slicing.legs
    slot_slices: tuple[tuple[tuple[int, int], ...], ...]

    def signature(self) -> tuple:
        return (self.program.signature(), self.slicing, self.slot_slices)

    def signature_digest(self) -> str:
        """Stable hex digest of :meth:`signature` (shared canonical
        encoder) — what sliced-plan artifacts persist on disk."""
        from tnc_tpu.utils.digest import stable_digest

        return stable_digest(self.signature())


class SliceYield(Exception):
    """A sliced execution yielded voluntarily at a checkpoint boundary
    (``on_slice`` returned True): the partial accumulator is persisted
    (when a checkpoint is armed) and ``cursor`` names the next slice to
    run. Re-invoking the same call resumes bit-identically from the
    checkpoint — the mechanism behind priority preemption in
    :mod:`tnc_tpu.serve.elastic`. Not an error: the caller chose to be
    interrupted."""

    def __init__(self, cursor: int):
        super().__init__(f"sliced execution yielded at slice {cursor}")
        self.cursor = int(cursor)


def build_sliced_program(
    tn: CompositeTensor, contract_path: ContractionPath, slicing: Slicing
) -> SlicedProgram:
    """Compile ``tn``'s path with ``slicing.legs`` removed from every leaf."""
    removed = set(slicing.legs)
    position = {leg: k for k, leg in enumerate(slicing.legs)}

    slot_slices: list[tuple[tuple[int, int], ...]] = []

    def reduce_tensor(t: LeafTensor) -> LeafTensor:
        info = tuple(
            (axis, position[leg])
            for axis, leg in enumerate(t.legs)
            if leg in removed
        )
        slot_slices.append(info)
        reduced = LeafTensor(
            [l for l in t.legs if l not in removed],
            [d for l, d in t.edges() if l not in removed],
            t.data,
        )
        return reduced

    def reduce_network(tensors: Sequence) -> CompositeTensor:
        out = CompositeTensor()
        # First pass: leaves in order (matching build_program slot order),
        # composites recursed afterwards in index order.
        reduced_children: list = []
        for child in tensors:
            if isinstance(child, CompositeTensor):
                reduced_children.append(None)
            else:
                reduced_children.append(reduce_tensor(child))
        for idx, child in enumerate(tensors):
            if isinstance(child, CompositeTensor):
                reduced_children[idx] = reduce_network(child.tensors)
        for c in reduced_children:
            out.push_tensor(c)
        return out

    if contract_path.nested:
        # Slicing currently targets flat paths (the distributed layer slices
        # within partitions instead).
        raise ValueError("Sliced execution expects a flat path")

    reduced_tn = reduce_network(tn.tensors)
    program = build_program(reduced_tn, contract_path)
    return SlicedProgram(program, slicing, tuple(slot_slices))


def kahan_add(s, c, x):
    """One compensated (Kahan) accumulation step over arrays.

    Returns ``(s', c')`` with ``s' + c'`` carrying the running sum to ~2
    ulp *independent of the number of steps* — the slice loop adds up to
    tens of thousands of contributions whose total cancels to orders of
    magnitude below the individual terms (a single Sycamore amplitude vs
    per-slice partial sums), where plain f32 accumulation loses the
    1e-5 parity target (VERDICT r3 #2). XLA does not reassociate
    floating-point adds by default, so the compensation survives jit
    (verified by tests/test_kahan.py under jax.jit).

    >>> import numpy as np
    >>> s = c = np.float32(1.0)
    >>> c = np.float32(0.0)
    >>> for _ in range(100):          # plain f32 sum would stay at 1.0
    ...     s, c = kahan_add(s, c, np.float32(1e-8))
    >>> 9e-07 < float(s + c) - 1.0 < 1.1e-06
    True
    """
    y = x + c
    t = s + y
    return t, y - (t - s)


def index_buffer(xp, arr, info, indices):
    """Pin ``arr``'s sliced axes to the given slice ``indices``.

    ``info`` is the slot's ``slot_slices`` entry: ((axis, slice_pos), …)
    ordered by axis. Shared by the on-device loop and chunked executors.
    """
    view = arr
    offset = 0
    for axis, pos in info:
        view = xp.take(view, indices[pos], axis=axis - offset)
        offset += 1
    return view


def _slice_indices(slicing: Slicing, s: int) -> list[int]:
    """Mixed-radix decomposition of flat slice id ``s``."""
    idx = []
    for d in reversed(slicing.dims):
        idx.append(s % d)
        s //= d
    idx.reverse()
    return idx


def execute_sliced_numpy(
    sp: SlicedProgram,
    arrays: Sequence[np.ndarray],
    dtype=np.complex128,
    max_slices: int | None = None,
    hoist: bool = False,
    ckpt: str | None = None,
    step_spans: bool | None = None,
    slice_range: tuple[int, int] | None = None,
    on_slice=None,
) -> np.ndarray:
    """CPU oracle: python loop over slices, sum of program results.

    ``max_slices`` caps the loop (partial sum) — used by benchmark
    baselines that extrapolate from a slice subset. ``hoist=True``
    computes the slice-invariant stem once and loops only the residual
    program (numerically identical — the same step kernels run in the
    same order, just not once per slice). ``ckpt`` (or ``TNC_TPU_CKPT``)
    arms slice-range checkpointing — the partial sum + cursor persist
    and an interrupted oracle run resumes bit-identically
    (:mod:`tnc_tpu.resilience.checkpoint`); minutes-per-slice oracle
    work is exactly what should never restart from slice 0.

    ``step_spans``: per-step timing spans (predicted flops/bytes next
    to measured wall time — the calibration input). Default (``None``):
    on whenever tracing is on. Callers that wall-clock this function as
    a published baseline pass ``False`` so span bookkeeping never sits
    inside their timed region (``bench.py`` takes its calibration
    sample from a separate untimed pass).

    ``slice_range=(lo, hi)``: partial sum over slice ids ``[lo, hi)``
    only — the multi-host serving shard shape (each host covers a
    contiguous range; the root sums the range partials in range order).
    Mutually exclusive with ``max_slices``. ``ckpt`` composes with a
    range since the elastic fleet (:mod:`tnc_tpu.serve.elastic`): the
    range partial checkpoints its own cursor + accumulator (signature
    includes the range), so a range shard lost to a dead worker resumes
    bit-identically on a survivor.

    ``on_slice``: optional ``cb(next_cursor) -> bool`` invoked after
    every completed slice. Returning True forces a checkpoint save (when
    armed) and raises :class:`SliceYield` — cooperative preemption at a
    slice boundary; the same call re-invoked resumes from the
    checkpoint.
    """
    from tnc_tpu.resilience import checkpoint as _ckpt
    from tnc_tpu.resilience import faultinject as _faults

    full = [np.asarray(a, dtype=dtype) for a in arrays]
    if hoist:
        from tnc_tpu.ops.hoist import hoist_sliced_program, run_prelude

        hp = hoist_sliced_program(sp)
        if not hp.is_noop:
            with obs.span(
                "sliced.prelude", steps=len(hp.prelude_steps), executor="numpy"
            ) as osp:
                full = run_prelude(np, hp, full)
                if obs.enabled():
                    pre = [ps.step for ps in hp.prelude_steps]
                    osp.add(
                        flops=steps_flops(pre),
                        bytes=steps_bytes(pre, np.dtype(dtype).itemsize),
                    )
            sp = hp.residual
    acc = np.zeros(sp.program.stored_result_shape, dtype=dtype)
    num = sp.slicing.num_slices
    if max_slices is not None:
        num = min(num, max_slices)
    if slice_range is not None:
        if max_slices is not None:
            raise ValueError(
                "slice_range is mutually exclusive with max_slices"
            )
        lo, hi = slice_range
        lo = max(0, int(lo))
        hi = min(int(hi), sp.slicing.num_slices)
        ckpt_path = _ckpt.resolve_ckpt(ckpt)
        mgr = None
        start = lo
        if ckpt_path is not None:
            # the range rides the signature: a (lo, hi) shard's
            # accumulator must never resume a different shard of the
            # same program (and arrays_digest keeps different leaf data
            # — different bitstrings — apart, as in the full-run path)
            sig = _ckpt.signature_hash(
                "numpy-range-v1", sp.signature(), str(np.dtype(dtype)),
                lo, hi, hoist, _ckpt.arrays_digest(arrays),
            )
            mgr = _ckpt.SliceCheckpoint(ckpt_path, sig)
            loaded = mgr.load()
            if loaded is not None:
                start, (saved,) = loaded
                start = max(lo, min(int(start), hi))
                acc = np.asarray(saved, dtype=dtype)
        with obs.span("sliced.range", lo=lo, hi=hi):
            for s in range(start, hi):
                _faults.fault_point("sliced.slice", s=s)
                indices = _slice_indices(sp.slicing, s)
                buffers = [
                    index_buffer(np, arr, info, indices)
                    for arr, info in zip(full, sp.slot_slices)
                ]
                acc = acc + _run_steps(np, sp.program, buffers)
                if mgr is not None:
                    mgr.maybe_save(s + 1, lambda _a=acc: [_a])
                if on_slice is not None and s + 1 < hi and on_slice(s + 1):
                    if mgr is not None:
                        mgr.save(s + 1, [acc])
                    raise SliceYield(s + 1)
        if mgr is not None:
            mgr.finalize()
        return acc.reshape(sp.program.result_shape)
    ckpt_path = _ckpt.resolve_ckpt(ckpt)
    mgr = None
    start = 0
    if ckpt_path is not None:
        # arrays_digest: the program signature is structural — same
        # circuit with different leaf data must not cross-resume
        sig = _ckpt.signature_hash(
            "numpy-v1", sp.signature(), str(np.dtype(dtype)), num, hoist,
            _ckpt.arrays_digest(arrays),
        )
        mgr = _ckpt.SliceCheckpoint(ckpt_path, sig)
        loaded = mgr.load()
        if loaded is not None:
            start, (saved,) = loaded
            start = max(0, min(start, num))
            acc = np.asarray(saved, dtype=dtype)
    # per-step spans (predicted flops/bytes + measured wall time) are
    # on by default for the synchronous oracle under tracing — the
    # richest CPU-side calibration sample (obs.calibrate)
    step_timed = obs.enabled() and (step_spans is None or step_spans)
    item_bytes = float(np.dtype(dtype).itemsize)
    with obs.span("sliced.residual", executor="numpy") as osp:
        for s in range(start, num):
            _faults.fault_point("sliced.slice", s=s)
            indices = _slice_indices(sp.slicing, s)
            buffers = [
                index_buffer(np, arr, info, indices)
                for arr, info in zip(full, sp.slot_slices)
            ]
            if step_timed:
                contrib = run_steps_timed(
                    np, sp.program, buffers, item_bytes
                )
            else:
                contrib = _run_steps(np, sp.program, buffers)
            acc = acc + contrib
            if mgr is not None:
                mgr.maybe_save(s + 1, lambda _a=acc: [_a])
            if on_slice is not None and s + 1 < num and on_slice(s + 1):
                if mgr is not None:
                    mgr.save(s + 1, [acc])
                raise SliceYield(s + 1)
        if obs.enabled():
            osp.add(
                slices=num - start,
                flops=(num - start) * steps_flops(sp.program.steps),
                bytes=(num - start)
                * steps_bytes(sp.program.steps, item_bytes),
            )
    if mgr is not None:
        mgr.finalize()
    return acc.reshape(sp.program.result_shape)


_PAR_STATE: dict = {}


def _par_init(blob):
    import pickle
    import zlib

    _PAR_STATE["sp"], _PAR_STATE["arrays"] = pickle.loads(
        zlib.decompress(blob)
    )


def _par_slice(s: int):
    sp = _PAR_STATE["sp"]
    full = _PAR_STATE["arrays"]
    indices = _slice_indices(sp.slicing, s)
    buffers = [
        index_buffer(np, arr, info, indices)
        for arr, info in zip(full, sp.slot_slices)
    ]
    return np.asarray(_run_steps(np, sp.program, buffers))


def sliced_partials_numpy(
    sp: SlicedProgram,
    arrays: Sequence[np.ndarray],
    dtype=np.complex128,
    slice_ids: Sequence[int] | None = None,
    workers: int | None = None,
    hoist: bool = False,
) -> np.ndarray:
    """Per-slice CPU-oracle results, stacked ``(n,) + result_shape``.

    Slices are embarrassingly independent, so on a many-core host they
    fan out over a spawn-safe process pool (the same discipline as the
    SA search pool, ``repartitioning/simulated_annealing.py`` — fork is
    unsafe once JAX's runtime threads exist); on a 1-core host the loop
    runs serially. Returning *per-slice* results (not the sum) lets the
    benchmark cache the oracle on disk and serve any prefix-sum parity
    sample later without redoing minutes-per-slice numpy work
    (VERDICT r3 weak #3). ``hoist=True`` runs the invariant stem once
    in this process and ships only the residual program (plus cached
    intermediates) to the pool workers."""
    import concurrent.futures
    import multiprocessing
    import pickle
    import zlib

    ids = (
        list(slice_ids)
        if slice_ids is not None
        else list(range(sp.slicing.num_slices))
    )
    full = [np.asarray(a, dtype=dtype) for a in arrays]
    if hoist:
        from tnc_tpu.ops.hoist import hoist_sliced_program, run_prelude

        hp = hoist_sliced_program(sp)
        if not hp.is_noop:
            full = [np.asarray(a) for a in run_prelude(np, hp, full)]
            sp = hp.residual
    if workers is None:
        workers = min(os.cpu_count() or 1, len(ids))
    parts: list[np.ndarray] | None = None
    if workers > 1 and len(ids) > 1:
        blob = zlib.compress(pickle.dumps((sp, full)), 1)
        try:
            ctx = multiprocessing.get_context("spawn")
            with concurrent.futures.ProcessPoolExecutor(
                max_workers=workers, mp_context=ctx, initializer=_par_init,
                initargs=(blob,),
            ) as pool:
                parts = list(pool.map(_par_slice, ids))
        except Exception:  # pool/pickle failure: the serial oracle is law
            parts = None
    if parts is None:
        parts = []
        for s in ids:
            indices = _slice_indices(sp.slicing, s)
            buffers = [
                index_buffer(np, arr, info, indices)
                for arr, info in zip(full, sp.slot_slices)
            ]
            parts.append(np.asarray(_run_steps(np, sp.program, buffers)))
    shape = (len(ids),) + tuple(sp.program.result_shape)
    return np.stack(parts).reshape(shape)


def execute_sliced_numpy_parallel(
    sp: SlicedProgram,
    arrays: Sequence[np.ndarray],
    dtype=np.complex128,
    max_slices: int | None = None,
    workers: int | None = None,
    hoist: bool = False,
) -> np.ndarray:
    """Sum of :func:`sliced_partials_numpy` over the first ``max_slices``
    slices — the process-parallel analogue of
    :func:`execute_sliced_numpy`."""
    num = sp.slicing.num_slices
    if max_slices is not None:
        num = max(1, min(num, max_slices))
    parts = sliced_partials_numpy(
        sp, arrays, dtype=dtype, slice_ids=range(num), workers=workers,
        hoist=hoist,
    )
    return np.sum(parts, axis=0, dtype=dtype)


def make_jax_sliced_fn(
    sp: SlicedProgram,
    split_complex: bool = False,
    precision: str | None = None,
    num_slices: int | None = None,
    unroll: int = 1,
    hoist: bool = False,
    slice_range: tuple[int, int] | None = None,
):
    """Build a jittable ``fn(full_buffers) -> result`` running the whole
    slice loop on device. In split mode, buffers and result are
    (real, imag) pairs of float arrays. ``num_slices`` caps the loop
    (partial sum over the first slices — benchmark subset mode).

    ``unroll > 1`` switches ``fori_loop`` for ``lax.scan(..., unroll=)``:
    XLA pessimizes while-loop bodies (~150× on the v5e north-star,
    TPU_EVIDENCE_r03.md), and an unrolled scan presents straight-line
    step groups instead — zero host dispatches per slice, chunked-class
    code inside the loop (scan handles any ``num % unroll`` remainder
    natively). Compile time grows with the unroll factor.

    ``hoist=True`` traces the slice-invariant prelude *before* the loop
    (:mod:`tnc_tpu.ops.hoist`): its outputs become loop constants — XLA
    keeps them resident in HBM — and only the residual steps run per
    iteration.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    hp = None
    if hoist:
        from tnc_tpu.ops.hoist import hoist_sliced_program

        cand = hoist_sliced_program(sp)
        if not cand.is_noop:
            hp = cand
    loop_sp = hp.residual if hp is not None else sp

    dims = sp.slicing.dims
    lo = 0
    num = sp.slicing.num_slices
    if slice_range is not None:
        # contiguous shard [lo, hi) — the multi-host serving shape
        if num_slices is not None:
            raise ValueError("slice_range and num_slices are exclusive")
        lo = max(0, int(slice_range[0]))
        num = min(int(slice_range[1]), num)
    elif num_slices is not None:
        num = max(1, min(num, num_slices))
    unroll = max(1, min(unroll, max(num - lo, 1)))

    def decompose(s):
        idx = []
        for d in reversed(dims):
            idx.append(s % d)
            s = s // d
        idx.reverse()
        return idx

    if split_complex:
        from tnc_tpu.ops.split_complex import plan_kernels, run_steps_split

        # the kernel promotion ladder over the per-slice loop body:
        # residual chains fuse into single Pallas dispatches, eligible
        # steps promote (the compiled-fn caches key on complex_mult_key,
        # so forced/auto traces never collide)
        loop_policy = plan_kernels(loop_sp.program)

        def one_slice(loop_buffers, s):
            indices = decompose(s)
            buffers = [
                (
                    index_buffer(jnp, re, info, indices),
                    index_buffer(jnp, im, info, indices),
                )
                for (re, im), info in zip(loop_buffers, loop_sp.slot_slices)
            ]
            return run_steps_split(
                jnp, loop_sp.program, buffers, precision, policy=loop_policy
            )

        def add(acc, contrib):
            (sr, cr), (si, ci) = acc
            sr, cr = kahan_add(sr, cr, contrib[0])
            si, ci = kahan_add(si, ci, contrib[1])
            return ((sr, cr), (si, ci))

        def zeros(full_buffers):
            dtype = full_buffers[0][0].dtype

            def z():
                return jnp.zeros(sp.program.stored_result_shape, dtype=dtype)

            return ((z(), z()), (z(), z()))

        def finish(acc):
            (sr, cr), (si, ci) = acc
            return (sr + cr, si + ci)

    else:

        def one_slice(loop_buffers, s):
            buffers = [
                index_buffer(jnp, arr, info, decompose(s))
                for arr, info in zip(loop_buffers, loop_sp.slot_slices)
            ]
            return _run_steps(jnp, loop_sp.program, list(buffers))

        def add(acc, contrib):
            return kahan_add(acc[0], acc[1], contrib)

        def zeros(full_buffers):
            def z():
                return jnp.zeros(
                    sp.program.stored_result_shape, dtype=full_buffers[0].dtype
                )

            return (z(), z())

        def finish(acc):
            return acc[0] + acc[1]

    def prepare(full_buffers):
        """Original buffers → loop buffers (prelude traced pre-loop)."""
        if hp is None:
            return full_buffers
        from tnc_tpu.ops.hoist import run_prelude

        return run_prelude(
            jnp, hp, list(full_buffers), split_complex, precision
        )

    if unroll <= 1:

        def fn(full_buffers):
            loop_buffers = prepare(full_buffers)

            def body(s, acc):
                return add(acc, one_slice(loop_buffers, s))

            return finish(lax.fori_loop(lo, num, body, zeros(full_buffers)))

    else:

        def fn(full_buffers):
            loop_buffers = prepare(full_buffers)

            def body(acc, s):
                return add(acc, one_slice(loop_buffers, s)), None

            acc, _ = lax.scan(
                body, zeros(full_buffers), jnp.arange(lo, num), unroll=unroll
            )
            return finish(acc)

    jitted = jax.jit(fn)
    hoisted = hp is not None
    # prelude + loop live inside ONE jitted dispatch here, so a single
    # span covers both; its flop counter is the hoisted total (prelude
    # once + residual per slice)
    total_flops = (num - lo) * steps_flops(loop_sp.program.steps)
    total_elem_bytes = (num - lo) * steps_bytes(loop_sp.program.steps, 1.0)
    if hp is not None:
        pre = [ps.step for ps in hp.prelude_steps]
        total_flops += steps_flops(pre)
        total_elem_bytes += steps_bytes(pre, 1.0)

    def run(full_buffers, _jitted=jitted):
        if not obs.enabled():
            return _jitted(full_buffers)
        first = full_buffers[0]
        item = (
            2.0 * first[0].dtype.itemsize
            if isinstance(first, tuple)
            else float(first.dtype.itemsize)
        )
        with obs.span(
            "sliced.loop", hoisted=hoisted, executor="loop"
        ) as osp:
            out = _jitted(full_buffers)
            osp.add(
                slices=num,
                flops=total_flops,
                bytes=total_elem_bytes * item,
            )
            return out

    return run
