"""HBM budget modeling and enforcement for sliced execution.

The reference computes memory requirements analytically before running
(``contractionpath/contraction_cost.rs:254-264``,
``book/src/parallelization.md`` — "memory requirements can already be
computed theoretically") and the benchmark picks configurations that fit
node RAM. On TPU the binding constraint is tighter — a single chip's HBM
— and the *physical* footprint differs from the logical element count
because f32 buffers are stored in (sublane × 128-lane) tiles: a trailing
dim below 128 pads up to it.

This module is the executor-side guardrail the round-2 bench lacked
(BENCH_r02 compiled a 34 GB padded buffer into 16 GB of HBM): it models
the padded footprint of a compiled program step by step and clamps the
chunked executor's ``slice_batch`` — or reports that a deeper slicing
target is needed — so the plan provably fits before anything is
dispatched to the device.
"""

from __future__ import annotations

import logging
import math
import os
from dataclasses import dataclass

from tnc_tpu import obs

logger = logging.getLogger(__name__)

_LANE = 128

# device_kind substring → HBM bytes (public spec sheets)
_HBM_BYTES = {
    "v2": 8 << 30,
    "v3": 16 << 30,
    "v4": 32 << 30,
    "v5 lite": 16 << 30,
    "v5e": 16 << 30,
    "v5p": 95 << 30,
    "v6 lite": 32 << 30,
    "v6e": 32 << 30,
}


def device_hbm_bytes(device=None) -> int:
    """Usable accelerator memory for ``device`` (default: first device).

    Order: ``TNC_TPU_HBM_BYTES`` env override → live ``memory_stats()``
    → device-kind table → 16 GiB fallback.
    """
    env = os.environ.get("TNC_TPU_HBM_BYTES")
    if env:
        return int(env)
    if device is None:
        import jax

        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # pragma: no cover - backend-dependent
        pass
    kind = getattr(device, "device_kind", "").lower()
    for tag, n in _HBM_BYTES.items():
        if tag in kind:
            return n
    if getattr(device, "platform", "") == "cpu":
        return 64 << 30  # host RAM-class budget for the CPU backend
    return 16 << 30


def padded_elems(shape: tuple[int, ...]) -> int:
    """Tile-padded element count of an f32 buffer: the minor dim pads up
    to 128 (XLA shrinks sublane tiles, so the second-minor does not pad).

    >>> padded_elems((4, 128)), padded_elems((4, 2)), padded_elems((1024,))
    (512, 512, 1024)
    """
    if not shape:
        return 1
    n = math.prod(shape[:-1]) if len(shape) > 1 else 1
    minor = shape[-1]
    return n * (-(-minor // _LANE) * _LANE if minor < _LANE else minor)


@dataclass(frozen=True)
class PeakEstimate:
    peak_bytes: int  # modeled peak HBM of one slice-batch execution
    peak_step: int  # step index at the peak
    bytes_per_batch_unit: int  # marginal bytes per +1 slice in the batch


def program_peak_bytes(
    program,
    *,
    split_complex: bool = True,
    dtype_bytes: int = 4,
    batch: int = 1,
) -> PeakEstimate:
    """Model the padded peak HBM of executing ``program`` with a leading
    slice-batch of ``batch``.

    Per step the working set is: all live stored buffers, both post-perm
    operand materializations, the dot output, and (split mode) one extra
    output-sized Gauss temporary (k1 lives while k2/k3 are built).
    """
    parts = 2 if split_complex else 1
    per_elem = dtype_bytes * parts

    live: dict[int, int] = {}
    for slot in range(program.num_inputs):
        live[slot] = 0  # leaf shapes are tiny; counted as free
    # leaves: caller may refine; treat as negligible (gates) but keep a
    # floor of one tile each
    leaf_bytes = program.num_inputs * 8 * _LANE * per_elem

    peak = leaf_bytes
    peak_step = -1
    for i, st in enumerate(program.steps):
        out = padded_elems(st.out_store)
        working = (
            sum(live.values())
            + padded_elems(tuple(st.a_dot))
            + padded_elems(tuple(st.b_dot))
            + out * (2 if split_complex else 1)  # dot out + gauss temp
        )
        cur = leaf_bytes + working * per_elem * batch
        if cur > peak:
            peak = cur
            peak_step = i
        live[st.lhs] = out
        live.pop(st.rhs, None)

    unit = (peak - leaf_bytes) // max(batch, 1)
    return PeakEstimate(int(peak), peak_step, int(unit))


def clamp_slice_batch(
    program,
    requested_batch: int,
    *,
    device=None,
    split_complex: bool = True,
    dtype_bytes: int = 4,
    safety: float = 0.75,
    hbm_bytes: int | None = None,
) -> int:
    """Largest batch ≤ ``requested_batch`` whose modeled peak fits in
    ``safety`` × HBM. Returns at least 1 (a batch of one either fits or
    the caller must slice deeper — see :func:`fits_hbm`)."""
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes(device)
    budget = int(hbm_bytes * safety)
    est = program_peak_bytes(
        program, split_complex=split_complex, dtype_bytes=dtype_bytes, batch=1
    )
    if est.bytes_per_batch_unit <= 0:
        return max(1, requested_batch)
    fixed = est.peak_bytes - est.bytes_per_batch_unit  # leaf/tile floor
    fit = max(1, (budget - fixed) // est.bytes_per_batch_unit)
    clamped = max(1, min(requested_batch, fit))
    if obs.enabled():
        # modeled peak of the batch the executor will actually run — the
        # trace-side record of the budget decision
        obs.gauge_set(
            "hbm.modeled_peak_bytes",
            fixed + clamped * est.bytes_per_batch_unit,
        )
        obs.gauge_set("hbm.budget_bytes", budget)
        if clamped < requested_batch:
            obs.counter_add("hbm.batch_clamped")
    if clamped < requested_batch:
        logger.info(
            "HBM budget: slice batch clamped %d -> %d "
            "(peak/unit %.2f GiB, budget %.2f GiB)",
            requested_batch,
            clamped,
            est.bytes_per_batch_unit / 2**30,
            budget / 2**30,
        )
    return clamped


def fits_hbm(
    program,
    *,
    batch: int = 1,
    device=None,
    split_complex: bool = True,
    dtype_bytes: int = 4,
    safety: float = 0.75,
    hbm_bytes: int | None = None,
) -> bool:
    """Does the modeled peak of one ``batch``-slice execution fit?"""
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes(device)
    est = program_peak_bytes(
        program, split_complex=split_complex, dtype_bytes=dtype_bytes, batch=batch
    )
    return est.peak_bytes <= hbm_bytes * safety


def compiled_peak_bytes(fn, arg_specs) -> int:
    """AOT-compile ``fn`` for ``arg_specs`` on the default device and
    return args+outputs+temps from XLA's memory analysis — the ground
    truth the model above approximates (used by the preflight tests)."""
    import jax

    compiled = jax.jit(fn).lower(*arg_specs).compile()
    ma = compiled.memory_analysis()
    return int(
        ma.argument_size_in_bytes + ma.output_size_in_bytes + ma.temp_size_in_bytes
    )
