"""Cost-model calibration: predicted-vs-measured per-step accounting.

The planner stack chooses paths, slicings and partitionings from
*predicted* flops/bytes (``contractionpath/contraction_cost.py``,
``ops/program.steps_flops``, the hoisted ``StemAccountant``), and the
executors record *measured* wall time per step when per-step timing is
on (``TNC_TPU_STEP_TIME``; always-on for the synchronous numpy oracle —
see :func:`tnc_tpu.ops.backends.run_steps_timed`). This module is where
the two ledgers meet:

- :func:`step_samples` collects ``step[i] MxK·KxN`` span records into
  (predicted flops, predicted bytes, measured seconds) samples;
- :func:`fit_device_model` least-squares-fits an effective device model
  ``time ≈ flops/F + bytes/B + c`` — achieved FLOP/s, achieved bytes/s,
  and a per-dispatch overhead — degrading gracefully to fewer terms
  when the samples can't identify all three;
- :func:`error_report` quantifies the cost model's prediction-error
  distribution and names the worst-mispredicted steps as a
  roofline-style table;
- :func:`calibration_report` bundles both into the plain-data
  ``calibration`` block ``bench.py`` embeds in its JSON record;
- :class:`CalibratedCostModel` converts planner flop counts into
  *seconds* under the fitted model — the slicing scorers
  (``slice_and_reconfigure``, ``find_parallel_slicing``,
  ``StemAccountant``) accept it in place of raw op counts, closing the
  plan → measure → replan loop: with a real per-dispatch overhead the
  planner stops treating 4× more slices as free.

>>> model = fit_device_model([
...     StepSample("step[0] a", 1e9, 0.0, 0.01),
...     StepSample("step[1] b", 2e9, 0.0, 0.02),
... ])
>>> round(model.flops_per_s / 1e9, 3)
100.0
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

from tnc_tpu.obs.core import MetricsRegistry, SpanRecord, get_registry

#: span-name prefix identifying per-step timing spans
#: (:func:`tnc_tpu.ops.program.step_label`)
STEP_PREFIX = "step["


@dataclass(frozen=True)
class StepSample:
    """One calibration observation: a step's predicted cost next to its
    measured wall time. ``source`` is the executor that measured it
    (``"numpy"`` / ``"jax"``) — samples from different executors must
    never share a fit (a host-measured millisecond says nothing about
    the device)."""

    name: str
    flops: float
    bytes: float
    dur_s: float
    source: str = ""


@dataclass(frozen=True)
class DeviceModel:
    """Fitted effective device model: ``predict_s(flops, bytes) =
    flops / flops_per_s + bytes / bytes_per_s + dispatch_s``.

    ``bytes_per_s`` is ``None`` when the samples could not identify a
    bandwidth term (all steps compute-bound, or flops ∝ bytes);
    ``terms`` records which terms the accepted fit used.
    """

    flops_per_s: float
    bytes_per_s: float | None
    dispatch_s: float
    n_samples: int
    terms: tuple[str, ...]

    def predict_s(self, flops: float, bytes_: float = 0.0) -> float:
        t = self.dispatch_s
        if flops and self.flops_per_s:
            t += flops / self.flops_per_s
        if bytes_ and self.bytes_per_s:
            t += bytes_ / self.bytes_per_s
        return t


def step_samples(
    records: Iterable[SpanRecord] | None = None,
    registry: MetricsRegistry | None = None,
) -> list[StepSample]:
    """Per-step samples from span records (default: the active
    registry). Only ``step[...]`` spans carrying a predicted cost
    qualify; everything else in the trace is ignored."""
    if records is None:
        reg = registry if registry is not None else get_registry()
        records = reg.span_records()
    out: list[StepSample] = []
    for rec in records:
        if not rec.name.startswith(STEP_PREFIX):
            continue
        flops = float(rec.args.get("flops", 0.0))
        nbytes = float(rec.args.get("bytes_in", 0.0)) + float(
            rec.args.get("bytes_out", 0.0)
        )
        if flops <= 0.0 and nbytes <= 0.0:
            continue
        out.append(
            StepSample(
                rec.name, flops, nbytes, rec.dur_ns / 1e9,
                str(rec.args.get("executor", "")),
            )
        )
    return out


def aggregate_samples(samples: Sequence[StepSample]) -> list[StepSample]:
    """One sample per distinct (step name, source), measured time =
    median over its occurrences (reps, slices) — damps scheduler noise
    before the fit without letting hot steps outvote the rest. Grouping
    includes the source so a host and a device measurement of the same
    step stay distinct samples."""
    groups: dict[tuple[str, str], list[StepSample]] = {}
    for s in samples:
        groups.setdefault((s.name, s.source), []).append(s)
    out = []
    for (name, source), grp in groups.items():
        med = float(np.median([g.dur_s for g in grp]))
        out.append(StepSample(name, grp[0].flops, grp[0].bytes, med, source))
    return out


def pick_source(samples: Sequence[StepSample]) -> str | None:
    """The executor whose samples a fit should use when a trace mixes
    several (a device run whose CPU-baseline/oracle phases also emitted
    numpy step spans): prefer the device (``jax``) samples — they are
    the hardware being modeled — else the most numerous source.
    ``None`` when there are no samples."""
    counts: dict[str, int] = {}
    for s in samples:
        counts[s.source] = counts.get(s.source, 0) + 1
    if not counts:
        return None
    if counts.get("jax", 0) >= 2:
        return "jax"
    return max(counts, key=lambda k: (counts[k], k))


_TERM_LADDER = (
    ("flops", "bytes", "dispatch"),
    ("flops", "dispatch"),
    ("flops", "bytes"),
    ("flops",),
)


def fit_device_model(samples: Sequence[StepSample]) -> DeviceModel | None:
    """Least-squares fit of the effective device model.

    Walks a term ladder — (flops, bytes, overhead) → (flops, overhead)
    → (flops, bytes) → (flops) — and accepts the first fit whose design
    matrix has full rank and whose coefficients are all physical
    (positive throughput, non-negative bandwidth/overhead); degenerate
    sample sets (e.g. every step the same shape) fall through to the
    aggregate-throughput estimate. Returns ``None`` below 2 usable
    samples.
    """
    usable = [
        s for s in samples if s.dur_s > 0.0 and (s.flops > 0.0 or s.bytes > 0.0)
    ]
    if len(usable) < 2:
        return None
    f = np.asarray([s.flops for s in usable], dtype=np.float64)
    b = np.asarray([s.bytes for s in usable], dtype=np.float64)
    y = np.asarray([s.dur_s for s in usable], dtype=np.float64)

    for terms in _TERM_LADDER:
        cols = []
        if "flops" in terms:
            cols.append(f)
        if "bytes" in terms:
            cols.append(b)
        if "dispatch" in terms:
            cols.append(np.ones_like(f))
        if len(usable) < len(cols):
            continue
        design = np.stack(cols, axis=1)
        try:
            coef, _res, rank, _sv = np.linalg.lstsq(design, y, rcond=None)
        except np.linalg.LinAlgError:  # pragma: no cover - defensive
            continue
        if rank < len(cols):
            continue
        named = dict(zip(terms, coef))
        if named.get("flops", 0.0) <= 0.0:
            continue
        for term in ("bytes", "dispatch"):
            # numerically-zero negatives from an exact solve are noise,
            # not an unphysical model
            if term in named and -1e-13 <= named[term] < 0.0:
                named[term] = 0.0
        if named.get("bytes", 0.0) < 0.0 or named.get("dispatch", 0.0) < 0.0:
            continue
        byte_coef = named.get("bytes", 0.0)
        return DeviceModel(
            flops_per_s=float(1.0 / named["flops"]),
            bytes_per_s=float(1.0 / byte_coef) if byte_coef > 0.0 else None,
            dispatch_s=float(named.get("dispatch", 0.0)),
            n_samples=len(usable),
            terms=terms,
        )

    total_f, total_y = float(f.sum()), float(y.sum())
    if total_f <= 0.0 or total_y <= 0.0:
        return None
    return DeviceModel(
        flops_per_s=total_f / total_y,
        bytes_per_s=None,
        dispatch_s=0.0,
        n_samples=len(usable),
        terms=("flops",),
    )


def error_report(
    samples: Sequence[StepSample], model: DeviceModel, top: int = 8
) -> dict:
    """Cost-model error distribution + the worst-mispredicted steps.

    Relative error is ``(predicted - measured) / measured`` per step;
    the percentiles are over its absolute value. ``worst_steps`` rows
    carry the step name (index + matmul dims), both times, the signed
    relative error, and the step's achieved FLOP/s — a roofline-style
    table of exactly the steps the cost model gets most wrong."""
    rows = []
    for s in samples:
        if s.dur_s <= 0.0:
            continue
        pred = model.predict_s(s.flops, s.bytes)
        rel = (pred - s.dur_s) / s.dur_s
        rows.append(
            {
                "step": s.name,
                "measured_s": float(f"{s.dur_s:.4e}"),
                "predicted_s": float(f"{pred:.4e}"),
                "rel_err": round(rel, 4),
                "flops": s.flops,
                "achieved_flops_per_s": float(f"{s.flops / s.dur_s:.4e}"),
            }
        )
    abs_errs = np.asarray([abs(r["rel_err"]) for r in rows]) if rows else None
    report = {
        "n_steps": len(rows),
        "error_p50": (
            round(float(np.percentile(abs_errs, 50)), 4) if rows else None
        ),
        "error_p90": (
            round(float(np.percentile(abs_errs, 90)), 4) if rows else None
        ),
        "error_max": round(float(abs_errs.max()), 4) if rows else None,
        "worst_steps": sorted(
            rows, key=lambda r: -abs(r["rel_err"])
        )[: max(top, 0)],
    }
    return report


def calibration_report(
    registry: MetricsRegistry | None = None,
    top: int = 8,
    source: str | None = None,
) -> dict | None:
    """The ``calibration`` block for the bench JSON record: fitted
    model (achieved FLOP/s, bytes/s, per-dispatch overhead) + the
    prediction-error distribution, from whatever per-step spans the
    run recorded. When the trace mixes executors the fit uses one
    ``source`` only (:func:`pick_source` unless given), recorded in
    the block — a host/device blend is not a device model. ``None``
    when no fit is possible (no step spans — e.g. tracing off, or a
    device-only run without ``TNC_TPU_STEP_TIME``)."""
    samples = aggregate_samples(step_samples(registry=registry))
    if source is None:
        source = pick_source(samples)
    samples = [s for s in samples if s.source == source]
    model = fit_device_model(samples)
    if model is None:
        return None
    report = {
        "source": source,
        "flops_per_s": float(f"{model.flops_per_s:.4e}"),
        "bytes_per_s": (
            float(f"{model.bytes_per_s:.4e}")
            if model.bytes_per_s is not None
            else None
        ),
        "dispatch_overhead_s": float(f"{model.dispatch_s:.4e}"),
        "fit_terms": list(model.terms),
        "n_samples": model.n_samples,
        # when the constants were fit: the staleness anchor
        # scripts/perf_gate.py warns on (a record whose calibration is
        # much older than the record was measured under drifted truth)
        "fitted_unix": time.time(),
    }
    report.update(error_report(samples, model, top=top))
    return report


def format_calibration_table(report: dict) -> str:
    """Human rendering of a :func:`calibration_report` (the bench
    stderr log): fitted constants, error percentiles, and the
    worst-step roofline rows."""
    lines = [
        "fitted device model: "
        f"{report['flops_per_s']:.3e} FLOP/s, "
        + (
            f"{report['bytes_per_s']:.3e} B/s, "
            if report.get("bytes_per_s")
            else "no bandwidth term, "
        )
        + f"{report['dispatch_overhead_s'] * 1e6:.1f} us/dispatch "
        f"({report['n_samples']} steps, "
        f"source={report.get('source') or '?'})",
        "cost-model |rel err|: "
        f"p50 {report['error_p50']:.1%}  p90 {report['error_p90']:.1%}  "
        f"max {report['error_max']:.1%}",
    ]
    head = (
        f"{'worst-mispredicted step':<34} {'measured':>12} {'predicted':>12} "
        f"{'rel_err':>8} {'GFLOP/s':>9}"
    )
    lines += [head, "-" * len(head)]
    for r in report.get("worst_steps", []):
        lines.append(
            f"{r['step']:<34} {r['measured_s']:>11.3e}s {r['predicted_s']:>11.3e}s "
            f"{r['rel_err']:>+7.1%} {r['achieved_flops_per_s'] / 1e9:>9.2f}"
        )
    return "\n".join(lines)


# -- roofline view over an exported trace -------------------------------


def roofline_rows(summary_rows: Sequence[dict]) -> list[dict]:
    """Per-stage roofline rows from :func:`tnc_tpu.obs.trace_summary`
    output: every stage that carried a flops or bytes counter gains its
    achieved throughput (GFLOP/s, GB/s) over its measured wall time —
    per-step spans and phase spans (``sliced.prelude`` / ``.residual``)
    alike."""
    out = []
    for r in summary_rows:
        flops = float(r.get("flops", 0.0))
        nbytes = (
            float(r.get("bytes", 0.0))
            + float(r.get("bytes_in", 0.0))
            + float(r.get("bytes_out", 0.0))
        )
        if flops <= 0.0 and nbytes <= 0.0:
            continue
        secs = r["total_ms"] / 1e3
        out.append(
            {
                "name": r["name"],
                "count": r["count"],
                "total_ms": r["total_ms"],
                "flops": flops,
                "bytes": nbytes,
                "gflops_per_s": (flops / secs / 1e9) if secs > 0 else 0.0,
                "gbytes_per_s": (nbytes / secs / 1e9) if secs > 0 else 0.0,
            }
        )
    return out


def format_roofline_table(rows: Sequence[dict]) -> str:
    """Aligned text table for :func:`roofline_rows` (the
    ``trace_summarize.py --roofline`` output)."""
    head = (
        f"{'stage':<36} {'count':>7} {'total_ms':>12} {'flops':>11} "
        f"{'bytes':>11} {'GFLOP/s':>9} {'GB/s':>8}"
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        lines.append(
            f"{r['name']:<36} {r['count']:>7} {r['total_ms']:>12.2f} "
            f"{r['flops']:>11.3g} {r['bytes']:>11.3g} "
            f"{r['gflops_per_s']:>9.2f} {r['gbytes_per_s']:>8.2f}"
        )
    return "\n".join(lines)


# -- planner-facing cost model ------------------------------------------


class CalibratedCostModel:
    """Seconds-domain cost for the slicing/partitioning scorers.

    Wraps a fitted :class:`DeviceModel` (or explicit constants) and
    converts planner op counts into predicted wall time, including the
    per-dispatch overhead raw flop counts are blind to — under it,
    slicing 4× deeper for a 5% flop saving correctly loses once the
    added dispatches outweigh the flops. Consumed by
    ``StemAccountant(cost_model=...)`` /
    ``slice_and_reconfigure(cost_model=...)`` /
    ``find_parallel_slicing(cost_model=...)``.

    >>> m = CalibratedCostModel(flops_per_s=1e9, dispatch_s=1e-3)
    >>> m.sliced_cost(0.0, 1e6, 4)        # 4 * (1 ms flops + 1 ms dispatch)
    0.008
    >>> m.sliced_cost(0.0, 4e6, 1) < m.sliced_cost(0.0, 1e6, 4)
    True
    """

    def __init__(
        self,
        flops_per_s: float,
        dispatch_s: float = 0.0,
        bytes_per_s: float | None = None,
    ):
        if flops_per_s <= 0.0:
            raise ValueError("flops_per_s must be positive")
        self.flops_per_s = float(flops_per_s)
        self.dispatch_s = max(float(dispatch_s), 0.0)
        self.bytes_per_s = (
            float(bytes_per_s) if bytes_per_s else None
        )

    @classmethod
    def from_device_model(cls, model: DeviceModel) -> "CalibratedCostModel":
        return cls(model.flops_per_s, model.dispatch_s, model.bytes_per_s)

    @classmethod
    def from_report(cls, report: dict) -> "CalibratedCostModel":
        """From a bench record's ``calibration`` block — replanning a
        workload with the constants a previous run measured."""
        return cls(
            report["flops_per_s"],
            report.get("dispatch_overhead_s", 0.0),
            report.get("bytes_per_s"),
        )

    @classmethod
    def from_registry(
        cls,
        registry: MetricsRegistry | None = None,
        source: str | None = None,
    ) -> "CalibratedCostModel | None":
        """Fit from the live registry's step spans (one source only —
        :func:`pick_source` unless given); ``None`` when no fit is
        possible."""
        samples = aggregate_samples(step_samples(registry=registry))
        if source is None:
            source = pick_source(samples)
        model = fit_device_model(
            [s for s in samples if s.source == source]
        )
        return cls.from_device_model(model) if model is not None else None

    def dispatch_equivalent_flops(self) -> float:
        """Flops whose predicted compute time equals ONE dispatch
        overhead — the scale below which a step is dispatch-dominated.
        The kernel promotion ladder's chain rung
        (:func:`tnc_tpu.ops.split_complex.plan_kernels`) fuses runs of
        such steps into one dispatch; a step several times this size
        gains nothing from fusion.

        >>> CalibratedCostModel(1e12, dispatch_s=2e-5).dispatch_equivalent_flops()
        20000000.0
        """
        return self.dispatch_s * self.flops_per_s

    def op_seconds(
        self, flops: float, nbytes: float = 0.0, dispatches: float = 1.0
    ) -> float:
        """Predicted seconds for a region of ``dispatches`` dispatched
        steps. ``dispatch_s`` is fitted from per-STEP samples, so a
        region running N steps pays it N times."""
        t = dispatches * self.dispatch_s + flops / self.flops_per_s
        if nbytes and self.bytes_per_s:
            t += nbytes / self.bytes_per_s
        return t

    def sliced_cost(
        self,
        invariant_flops: float,
        residual_flops: float,
        num_slices: int,
        steps_per_slice: float = 1.0,
        prelude_steps: float = 1.0,
    ) -> float:
        """Predicted seconds of a hoisted sliced execution: the
        invariant stem once (when non-empty), then per slice the
        residual flops plus the per-step overhead times the residual
        step count — the calibrated analogue of the planner's
        ``invariant + num_slices * residual`` flop formula. The fitted
        ``dispatch_s`` is a per-STEP constant, so callers that know the
        step split (``StemAccountant``) pass ``steps_per_slice`` /
        ``prelude_steps``; the default of 1 underestimates overhead for
        multi-step programs but stays monotone in the slice count."""
        prelude = (
            self.op_seconds(invariant_flops, dispatches=prelude_steps)
            if invariant_flops > 0.0
            else 0.0
        )
        return prelude + num_slices * self.op_seconds(
            residual_flops, dispatches=steps_per_slice
        )
