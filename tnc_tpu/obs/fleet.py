"""Fleet observability plane: cross-host trace propagation, a replica
registry with heartbeats, federated telemetry, and a crash flight
recorder.

Everything in :mod:`tnc_tpu.obs` up to here is process-local; a
multi-host serving fleet (``ClusterDispatcher`` / ``serve_cluster``)
leaves each replica with its own registry, its own trace file, and its
own ``/metrics`` — disconnected fragments. This module is the glue:

- :class:`TraceContext` — a serializable span-identity capsule (request
  ids, query kind, plan generation, dispatch sequence, root identity)
  that the root's dispatcher stashes in a thread-local around each
  batch (:func:`dispatch_context`), :class:`~tnc_tpu.serve.multihost.
  ClusterDispatcher` ships inside its broadcast command, and the worker
  adopts (:func:`adopt_trace_context`) so its ``serve.dispatch`` /
  ``partitioned.*`` / slice spans carry the ROOT's request ids — the
  merged fleet timeline attributes cross-host dispatch wall time to the
  same rids the single-host rollup uses.
- :class:`FleetRegistry` — replica roster on a shared directory using
  the plan-cache discipline (unique-tmp atomic JSON writes, mtime-based
  staleness, corrupt entries dropped and counted, never raised). Each
  replica heartbeats identity + queue/SLO state on a cadence
  (:class:`Heartbeat`); any reader gets a live roster with join /
  stale / leave / reap transitions surfaced as obs counters + gauges.
- :class:`FleetAggregator` — root-side federation: scrapes every
  replica's ``/metrics`` (or falls back to heartbeat payloads), sums
  counters across replicas in deterministic order, keeps gauges and
  quantiles per-replica under a ``replica=`` label, and reports an
  honest pooled min/max envelope for quantile series — P² sketches do
  not merge exactly, so the endpoint never pretends they do. Feeds the
  ``/fleet`` route of :class:`~tnc_tpu.obs.http.TelemetryServer`.
- :class:`FlightRecorder` — ``TNC_TPU_FLIGHT_RECORDER=<dir>``: a
  bounded ring of recent closed spans plus a counter snapshot, dumped
  atomically on fatal exceptions, SIGTERM, interpreter exit, AND on a
  short periodic cadence — so even a SIGKILL (the fault-injection
  ``kill`` kind, or a real preemption) leaves a parseable postmortem
  artifact no more than one flush interval stale.

>>> ctx = TraceContext(riders="r1,r2", kind="amplitude", generation=3)
>>> TraceContext.from_obj(ctx.to_obj()) == ctx
True
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import sys
import threading
import time
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Mapping

from tnc_tpu.obs.core import get_registry

import tnc_tpu.obs.core as _core

logger = logging.getLogger(__name__)


# -- replica identity ---------------------------------------------------


def _procs() -> tuple[int, int]:
    """(process_count, process_index) — (1, 0) without a distributed
    runtime, so every caller degrades to single-replica behaviour."""
    try:
        import jax

        return int(jax.process_count()), int(jax.process_index())
    except Exception:  # noqa: BLE001 — no jax / not initialized
        return 1, 0


def replica_identity() -> dict:
    """This process's fleet identity: distributed process index/count,
    hostname, pid. Every span file, heartbeat, flight-recorder dump and
    federated metric row carries (a projection of) this dict.

    >>> ident = replica_identity()
    >>> sorted(ident)
    ['host', 'pid', 'process', 'process_count']
    """
    n, me = _procs()
    return {
        "process": me,
        "process_count": n,
        "host": socket.gethostname(),
        "pid": os.getpid(),
    }


def replica_name(identity: Mapping | None = None) -> str:
    """Short roster/label name for a replica — ``p<process_index>``.
    Unique within one ``jax.distributed`` fleet; callers outside a
    distributed runtime (tests, ad-hoc processes) should pass their own
    name to :class:`FleetRegistry` instead.

    >>> replica_name({"process": 3})
    'p3'
    """
    ident = identity if identity is not None else replica_identity()
    return f"p{ident.get('process', 0)}"


# -- cross-host trace propagation --------------------------------------


@dataclass(frozen=True)
class TraceContext:
    """The span identity a dispatch carries across the host boundary.

    ``riders`` is the same comma-joined request-id list the root's
    ``serve.dispatch`` span carries (``"r1,r2,..."``) — the merged
    trace rollup attributes each span's wall time over exactly this
    list, so a worker span wearing the context is indistinguishable
    (for attribution) from root-side dispatch time.
    """

    riders: str = ""
    kind: str = "?"
    generation: int = 0
    seq: int = 0
    root_process: int = 0
    root_pid: int = 0

    def to_obj(self) -> dict:
        """Plain-dict form for the ``broadcast_object`` channel."""
        return {
            "riders": self.riders,
            "kind": self.kind,
            "generation": self.generation,
            "seq": self.seq,
            "root_process": self.root_process,
            "root_pid": self.root_pid,
        }

    @classmethod
    def from_obj(cls, obj) -> "TraceContext | None":
        """Inverse of :meth:`to_obj`; tolerant of ``None`` and unknown
        keys (a version-skewed root must not crash a worker)."""
        if not isinstance(obj, Mapping):
            return None
        return cls(
            riders=str(obj.get("riders", "")),
            kind=str(obj.get("kind", "?")),
            generation=int(obj.get("generation", 0) or 0),
            seq=int(obj.get("seq", 0) or 0),
            root_process=int(obj.get("root_process", 0) or 0),
            root_pid=int(obj.get("root_pid", 0) or 0),
        )


_TLS = threading.local()


def current_dispatch_context() -> TraceContext | None:
    """The TraceContext of the dispatch currently executing on this
    thread (set by the service around its dispatcher call), or None."""
    return getattr(_TLS, "dispatch_ctx", None)


class _DispatchCtx:
    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: TraceContext):
        self._ctx = ctx

    def __enter__(self) -> TraceContext:
        self._prev = getattr(_TLS, "dispatch_ctx", None)
        _TLS.dispatch_ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> bool:
        _TLS.dispatch_ctx = self._prev
        return False


def dispatch_context(
    riders: str = "", kind: str = "?", generation: int = 0, seq: int = 0
) -> _DispatchCtx:
    """Context manager the serving layer wraps around one batch
    dispatch: while active, :func:`current_dispatch_context` answers
    with this batch's identity, so a pluggable dispatcher (whose
    ``fn(bound, bits, backend)`` signature carries no request ids) can
    recover the rid list to ship across hosts.

    >>> with dispatch_context(riders="r7", kind="amplitude") as ctx:
    ...     current_dispatch_context().riders
    'r7'
    >>> current_dispatch_context() is None
    True
    """
    n, me = _procs()
    return _DispatchCtx(TraceContext(
        riders=riders, kind=kind, generation=generation, seq=seq,
        root_process=me, root_pid=os.getpid(),
    ))


def adopt_trace_context(ctx: TraceContext | None):
    """Worker-side adoption: every span opened on this thread while the
    context manager is active carries the root's request ids (and the
    dispatch's generation/sequence) as span args — ``serve.dispatch``,
    ``partitioned.*`` and slice spans all land in the merged timeline
    already attributed. No-op (identity) for a None context."""
    if ctx is None:
        return _core.trace_args()
    return _core.trace_args(
        riders=ctx.riders,
        generation=ctx.generation,
        seq=ctx.seq,
        root_process=ctx.root_process,
    )


# -- replica registry with heartbeats ----------------------------------


class FleetRegistry:
    """Replica roster on a shared directory — the same multi-writer
    discipline as :class:`~tnc_tpu.serve.plancache.PlanCache`: each
    write goes through a uniquely named temp file + atomic
    ``os.replace`` (readers never see a torn entry; the last complete
    write wins), staleness is judged by file mtime, and corrupt entries
    are deleted and counted, never raised.

    One file per replica (``hb-<name>.json``); :meth:`heartbeat`
    republishes it on a cadence (usually via :class:`Heartbeat`),
    :meth:`roster` reads the live view and surfaces join / stale /
    leave transitions as obs counters, :meth:`reap` garbage-collects
    entries that stayed stale past the reap threshold (a crashed
    replica's tombstone), and :meth:`retire` removes this replica's own
    entry for a clean leave (so the roster can tell shutdown from
    crash).

    >>> import tempfile
    >>> with tempfile.TemporaryDirectory() as d:
    ...     reg = FleetRegistry(d, name="p0")
    ...     _ = reg.heartbeat({"queue_depth": 0})
    ...     r = reg.roster()
    ...     (r["live"], r["replicas"][0]["name"])
    (1, 'p0')
    """

    def __init__(
        self,
        directory: str | Path,
        name: str | None = None,
        stale_after_s: float = 10.0,
        reap_after_s: float | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.identity = replica_identity()
        self.name = name if name is not None else replica_name(self.identity)
        self.stale_after_s = float(stale_after_s)
        self.reap_after_s = (
            float(reap_after_s) if reap_after_s is not None
            else 3.0 * self.stale_after_s
        )
        self._seq = 0
        self._last_beat: float | None = None  # monotonic
        self._lock = threading.Lock()
        # name -> "live" | "stale": the previous roster() view, so
        # transitions count exactly once per edge
        self._states: dict[str, str] = {}

    def _path(self, name: str) -> Path:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
        return self.directory / f"hb-{safe}.json"

    # -- writer side ---------------------------------------------------

    def heartbeat(self, payload: Mapping | None = None) -> str:
        """Atomically (re)publish this replica's entry. ``payload`` is
        the replica's self-reported state (queue depth, in-flight
        batch, SLO alerts, scrape URL, ...) and rides verbatim under
        ``"payload"``. Returns the entry path."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._last_beat = time.monotonic()
        doc = {
            "name": self.name,
            "identity": self.identity,
            "seq": seq,
            "time_unix": time.time(),
            "payload": dict(payload) if payload else {},
        }
        target = self._path(self.name)
        tmp = target.with_name(
            f"{target.name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
        )
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, target)
        except OSError:
            # a full/yanked shared volume must degrade observability,
            # never kill serving
            logger.warning("fleet: heartbeat write failed", exc_info=True)
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            _core.counter_add("fleet.heartbeat.errors")
            return str(target)
        _core.counter_add("fleet.heartbeats")
        return str(target)

    def last_heartbeat_age_s(self) -> float | None:
        """Seconds since THIS replica's last :meth:`heartbeat` (None
        before the first one) — the worker ``/healthz`` freshness
        field."""
        with self._lock:
            last = self._last_beat
        return None if last is None else time.monotonic() - last

    def retire(self) -> None:
        """Remove this replica's entry — a clean leave (vs. going
        stale, which is what a crash looks like)."""
        try:
            self._path(self.name).unlink(missing_ok=True)
        except OSError:
            pass

    # -- reader side ---------------------------------------------------

    def read(self) -> list[dict]:
        """Every parseable entry, with ``age_s`` (mtime-based) added.
        Corrupt files are deleted and counted, never raised — exactly
        the plan-cache contract."""
        out: list[dict] = []
        now = time.time()
        for path in sorted(self.directory.glob("hb-*.json")):
            try:
                with open(path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                age = max(now - path.stat().st_mtime, 0.0)
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                _core.counter_add("fleet.registry.corrupt_dropped")
                try:
                    path.unlink(missing_ok=True)
                except OSError:
                    pass
                continue
            if not isinstance(doc, dict):
                _core.counter_add("fleet.registry.corrupt_dropped")
                try:
                    path.unlink(missing_ok=True)
                except OSError:
                    pass
                continue
            doc["age_s"] = age
            doc.setdefault("name", path.stem[3:])
            out.append(doc)
        return out

    def roster(self) -> dict:
        """The live fleet view: one row per replica with its identity,
        heartbeat age, payload and ``state`` (``live`` when the entry's
        mtime is within ``stale_after_s``, else ``stale``). Join /
        went-stale / recovered / left transitions relative to the
        previous call are counted (``fleet.replica.*``) and the live /
        stale totals land as gauges — the autoscaler signal surface."""
        entries = self.read()
        rows = []
        states: dict[str, str] = {}
        for doc in entries:
            state = "live" if doc["age_s"] <= self.stale_after_s else "stale"
            states[doc["name"]] = state
            rows.append({
                "name": doc["name"],
                "state": state,
                "age_s": round(doc["age_s"], 3),
                "seq": doc.get("seq", 0),
                "identity": doc.get("identity", {}),
                "payload": doc.get("payload", {}),
            })
        transitions = {"joined": 0, "went_stale": 0, "recovered": 0,
                       "left": 0}
        with self._lock:
            prev = self._states
            for name, state in states.items():
                was = prev.get(name)
                if was is None:
                    transitions["joined"] += 1
                elif was == "live" and state == "stale":
                    transitions["went_stale"] += 1
                elif was == "stale" and state == "live":
                    transitions["recovered"] += 1
            for name in prev:
                if name not in states:
                    transitions["left"] += 1
            self._states = states
        for key, n in transitions.items():
            if n:
                _core.counter_add(f"fleet.replica.{key}", float(n))
        live = sum(1 for s in states.values() if s == "live")
        stale = len(states) - live
        _core.gauge_set("fleet.replicas.live", float(live))
        _core.gauge_set("fleet.replicas.stale", float(stale))
        return {
            "replicas": rows,
            "live": live,
            "stale": stale,
            "transitions": transitions,
        }

    def reap(self, reap_after_s: float | None = None) -> list[str]:
        """Delete entries whose mtime is older than ``reap_after_s``
        (default: the registry's, 3× the stale threshold). Returns the
        reaped names. A reaped replica that comes back simply
        re-joins on its next heartbeat."""
        threshold = (
            float(reap_after_s) if reap_after_s is not None
            else self.reap_after_s
        )
        now = time.time()
        reaped: list[str] = []
        for path in sorted(self.directory.glob("hb-*.json")):
            try:
                if now - path.stat().st_mtime <= threshold:
                    continue
                path.unlink()
            except OSError:
                continue
            name = path.stem[3:]
            reaped.append(name)
            with self._lock:
                self._states.pop(name, None)
        if reaped:
            _core.counter_add("fleet.replica.reaped", float(len(reaped)))
        return reaped


class Heartbeat:
    """Background heartbeat loop for one :class:`FleetRegistry` entry:
    publishes ``provider()`` every ``interval_s`` on a daemon thread
    until :meth:`stop` (which retires the entry — a clean leave — by
    default). Provider exceptions are swallowed and counted: a broken
    stats hook must degrade the heartbeat payload, not kill the
    cadence."""

    def __init__(
        self,
        registry: FleetRegistry,
        provider: Callable[[], Mapping] | None = None,
        interval_s: float = 2.0,
    ):
        self.registry = registry
        self.provider = provider
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _payload(self) -> dict:
        if self.provider is None:
            return {}
        try:
            return dict(self.provider())
        except Exception:  # noqa: BLE001 — keep the cadence
            _core.counter_add("fleet.heartbeat.provider_errors")
            logger.warning("fleet: heartbeat provider failed", exc_info=True)
            return {}

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.registry.heartbeat(self._payload())
            self._stop.wait(self.interval_s)

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.registry.heartbeat(self._payload())  # join immediately
        self._thread = threading.Thread(
            target=self._loop, name="tnc-fleet-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, retire: bool = True) -> None:
        thread, self._thread = self._thread, None
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=self.interval_s + 5.0)
        if retire:
            self.registry.retire()

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- federated telemetry ------------------------------------------------


def _series_family(series: str) -> str:
    return series.split("{", 1)[0]


def _series_without_replica(series: str) -> str:
    """Drop a ``replica="..."`` label from a rendered series key —
    counters sum across replicas per family+labels, so the source
    replica's identity (baked in by a worker's ``base_labels``) must
    not keep the series apart."""
    i = series.find('replica="')
    if i < 0:
        return series
    j = series.index('"', i + len('replica="')) + 1
    if series[j: j + 1] == ",":
        j += 1  # replica="x",rest  ->  rest
    elif series[i - 1: i] == ",":
        i -= 1  # head,replica="x"}  ->  head}
    out = series[:i] + series[j:]
    return out[:-2] if out.endswith("{}") else out


def _series_with_replica(series: str, replica: str) -> str:
    """Inject a ``replica="<name>"`` label into a rendered series key
    (idempotent: a series that already carries one — a worker endpoint
    labeled at the source — is returned unchanged)."""
    if 'replica="' in series:
        return series
    from tnc_tpu.obs.http import escape_label_value

    label = f'replica="{escape_label_value(replica)}"'
    if series.endswith("}"):
        head, _, rest = series.partition("{")
        return f"{head}{{{label},{rest}"
    return f"{series}{{{label}}}"


def merge_fleet_metrics(
    per_replica: Mapping[str, Mapping[str, float]],
    types: Mapping[str, str] | None = None,
) -> dict:
    """Merge per-replica Prometheus snapshots into one fleet view.

    - **counters** (family type ``counter``) are summed across replicas
      in sorted replica order — deterministic, so the fleet total is
      bit-equal to summing the per-replica registries yourself;
    - **gauges and summaries** are kept per-replica, each series
      re-keyed with a ``replica=`` label (P² quantile sketches cannot
      be merged exactly, so no pooled percentile is fabricated);
    - quantile series additionally get a pooled **min/max envelope**
      per family+labels: the honest cross-fleet bound ("the p99 of
      every replica lies in [lo, hi]"), which is all the sketches
      actually support.

    ``types`` maps family name → Prometheus type (from the ``# TYPE``
    lines); series from typeless sources (heartbeat payloads) fall back
    to the ``_total`` suffix convention for counter detection.

    >>> merged = merge_fleet_metrics(
    ...     {"p0": {"x_total": 2.0, "g": 1.0},
    ...      "p1": {"x_total": 3.0, "g": 5.0}},
    ...     types={"x_total": "counter", "g": "gauge"})
    >>> merged["counters"]["x_total"]
    5.0
    >>> sorted(merged["per_replica"])
    ['g{replica="p0"}', 'g{replica="p1"}']
    """
    types = dict(types or {})
    counters: dict[str, float] = {}
    per_rep: dict[str, float] = {}
    envelope: dict[str, dict] = {}
    for replica in sorted(per_replica):
        series_map = per_replica[replica]
        for series in sorted(series_map):
            value = float(series_map[series])
            fam = _series_family(series)
            ftype = types.get(fam)
            if ftype is None:
                ftype = "counter" if fam.endswith("_total") else "gauge"
            if ftype == "counter":
                key = _series_without_replica(series)
                counters[key] = counters.get(key, 0.0) + value
                continue
            per_rep[_series_with_replica(series, replica)] = value
            if ftype == "summary" and 'quantile="' in series:
                env = envelope.setdefault(
                    series, {"min": value, "max": value, "replicas": 0}
                )
                env["min"] = min(env["min"], value)
                env["max"] = max(env["max"], value)
                env["replicas"] += 1
    return {
        "replicas": sorted(per_replica),
        "counters": counters,
        "per_replica": per_rep,
        "quantile_envelope": envelope,
    }


class FleetAggregator:
    """Root-side federation: one object that knows every replica's
    scrape source and produces the ``/fleet`` body.

    Sources, in precedence order per replica:

    - ``endpoints`` — ``{name: base_url}`` scraped over HTTP via
      ``parse_prometheus`` (each replica's live ``TelemetryServer``);
    - ``local`` — ``(name, callable() -> prometheus_text)`` for the
      process hosting the aggregator (no HTTP round-trip to yourself);
    - heartbeat payloads from ``registry`` — a replica whose payload
      carries ``"url"`` is scraped; one that instead carries a
      ``"counters"`` dict (no port open) contributes those directly.

    Scrape failures are counted and the replica is reported under
    ``"unreachable"`` — a dead replica must not take the fleet view
    down with it.
    """

    def __init__(
        self,
        endpoints: Mapping[str, str] | Iterable[str] = (),
        registry: FleetRegistry | None = None,
        local: tuple[str, Callable[[], str]] | None = None,
        timeout_s: float = 3.0,
    ):
        if isinstance(endpoints, Mapping):
            self.endpoints = dict(endpoints)
        else:
            self.endpoints = {
                f"replica{i}": str(url)
                for i, url in enumerate(endpoints)
            }
        self.registry = registry
        self.local = local
        self.timeout_s = float(timeout_s)

    @staticmethod
    def _fetch(url: str, timeout_s: float) -> str:
        import urllib.request

        if not url.endswith("/metrics"):
            url = url.rstrip("/") + "/metrics"
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8")

    def _sources(self, roster: dict | None) -> dict[str, dict]:
        """name -> {"url": ...} | {"text": ...} | {"values": ...}."""
        sources: dict[str, dict] = {}
        if roster is not None:
            for row in roster["replicas"]:
                payload = row.get("payload", {})
                if payload.get("url"):
                    sources[row["name"]] = {"url": str(payload["url"])}
                elif isinstance(payload.get("counters"), dict):
                    sources[row["name"]] = {
                        "values": {
                            str(k): float(v)
                            for k, v in payload["counters"].items()
                        }
                    }
        for name, url in self.endpoints.items():
            sources[name] = {"url": url}
        if self.local is not None:
            name, render = self.local
            sources[name] = {"render": render}
        return sources

    def snapshot(self) -> dict:
        """Scrape + merge everything into the ``/fleet`` JSON body."""
        from tnc_tpu.obs.http import parse_prometheus, parse_prometheus_types

        roster = self.registry.roster() if self.registry is not None else None
        per_replica: dict[str, dict[str, float]] = {}
        types: dict[str, str] = {}
        unreachable: dict[str, str] = {}
        for name, src in sorted(self._sources(roster).items()):
            try:
                if "values" in src:
                    per_replica[name] = src["values"]
                    continue
                text = (
                    src["render"]() if "render" in src
                    else self._fetch(src["url"], self.timeout_s)
                )
                per_replica[name] = parse_prometheus(text)
                types.update(parse_prometheus_types(text))
            except Exception as exc:  # noqa: BLE001 — keep the fleet view up
                _core.counter_add("fleet.scrape.errors")
                unreachable[name] = f"{type(exc).__name__}: {exc}"
        merged = merge_fleet_metrics(per_replica, types)
        merged["unreachable"] = unreachable
        merged["note"] = (
            "counters are summed across replicas; gauges/quantiles are "
            "per-replica (P2 sketches do not merge exactly) with a "
            "pooled min/max envelope per quantile series"
        )
        if roster is not None:
            merged["roster"] = roster
        return merged


# -- crash flight recorder ----------------------------------------------

#: process-wide context stamped into every flight-recorder dump (the
#: ``context`` key): durable facts a postmortem needs that no span
#: carries — e.g. the cost-model generation that was pricing traffic at
#: crash time (``model_version``, set by the serving layer on adoption)
_flight_annotations: dict = {}
_flight_annotations_lock = threading.Lock()


def set_flight_annotation(**kwargs) -> None:
    """Merge key/value context into future flight-recorder dumps.
    Values must be JSON-serializable scalars; ``None`` deletes a key."""
    with _flight_annotations_lock:
        for key, value in kwargs.items():
            if value is None:
                _flight_annotations.pop(key, None)
            else:
                _flight_annotations[key] = value


def flight_annotations() -> dict:
    """The current annotation context (a copy)."""
    with _flight_annotations_lock:
        return dict(_flight_annotations)


class FlightRecorder:
    """Postmortem span ring: keeps the last ``capacity`` closed spans
    (read straight off the live obs registry — registry swaps are
    transparent) plus a counter/gauge snapshot, and dumps them
    atomically to ``<dir>/flight-<name>-<pid>.json``:

    - on a fatal exception (``sys.excepthook`` + ``threading
      .excepthook`` chains, original hooks still run),
    - on SIGTERM (handler chains to the previous one; the default
      disposition is re-delivered after the dump so termination
      semantics are preserved),
    - at interpreter exit, and
    - every ``flush_interval_s`` on a daemon thread — the reason a
      SIGKILL (uncatchable by definition) still leaves an artifact at
      most one interval stale.

    Arm it via ``TNC_TPU_FLIGHT_RECORDER=<dir>`` (see
    :func:`maybe_flight_recorder`, wired into ``obs.refresh_from_env``)
    or construct + :meth:`install` directly.
    """

    def __init__(
        self,
        directory: str | Path,
        capacity: int = 512,
        flush_interval_s: float = 1.0,
        name: str | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.capacity = int(capacity)
        self.flush_interval_s = float(flush_interval_s)
        self.identity = replica_identity()
        self.name = name if name is not None else replica_name(self.identity)
        self.path = self.directory / (
            f"flight-{self.name}-{os.getpid()}.json"
        )
        self._lock = threading.Lock()
        self._dumps = 0
        self._last_fingerprint: tuple | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._prev_excepthook = None
        self._prev_threading_hook = None
        self._prev_sigterm = None
        self._installed = False

    # -- dumping -------------------------------------------------------

    def _spans(self) -> list[dict]:
        reg = get_registry()
        recs = reg.recent_spans(self.capacity, include_open=True)
        return [
            {
                "name": r.name,
                "start_s": r.start_ns / 1e9,
                "dur_s": r.dur_ns / 1e9,
                "pid": r.pid,
                "tid": r.tid,
                "depth": r.depth,
                "args": {
                    k: v if isinstance(v, (str, int, float, bool, type(None)))
                    else str(v)
                    for k, v in r.args.items()
                },
            }
            for r in recs
        ]

    def dump(self, reason: str) -> str | None:
        """Write the ring + metric snapshot atomically (unique tmp +
        ``os.replace`` — a dump racing a SIGKILL leaves either the
        previous complete file or the new one, never a torn one).
        Never raises. Returns the path, or None on failure."""
        reg = get_registry()
        try:
            with self._lock:
                self._dumps += 1
                doc = {
                    "reason": reason,
                    "written_unix": time.time(),
                    "replica": self.identity,
                    "name": self.name,
                    "dumps": self._dumps,
                    "spans": self._spans(),
                    "counters": {
                        _core.format_metric_key(k): v
                        for k, v in reg.counters().items()
                    },
                    "gauges": {
                        _core.format_metric_key(k): v
                        for k, v in reg.gauges().items()
                    },
                    "dropped_spans": reg.dropped_spans(),
                    "context": flight_annotations(),
                }
                tmp = self.path.with_name(
                    f"{self.path.name}.{os.getpid()}."
                    f"{uuid.uuid4().hex[:8]}.tmp"
                )
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(doc, fh)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, self.path)
            return str(self.path)
        except Exception:  # noqa: BLE001 — a recorder must never crash its host
            logger.warning("fleet: flight-recorder dump failed",
                           exc_info=True)
            return None

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            reg = get_registry()
            fp = (id(reg), len(reg.recent_spans(1)) and
                  reg.recent_spans(1)[-1].start_ns,
                  reg.dropped_spans())
            if fp != self._last_fingerprint:
                self._last_fingerprint = fp
                self.dump("periodic")

    # -- hooks ---------------------------------------------------------

    def _on_exception(self, exc_type, exc, tb) -> None:
        self.dump(f"exception:{exc_type.__name__}")
        if self._prev_excepthook is not None:
            self._prev_excepthook(exc_type, exc, tb)

    def _on_thread_exception(self, hook_args) -> None:
        et = hook_args.exc_type.__name__ if hook_args.exc_type else "?"
        self.dump(f"thread-exception:{et}")
        if self._prev_threading_hook is not None:
            self._prev_threading_hook(hook_args)

    def _on_sigterm(self, signum, frame) -> None:
        self.dump("sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
            return
        if prev == signal.SIG_IGN:
            return
        # default disposition: restore it and re-deliver, so the
        # process still dies of SIGTERM exactly as unrecorded code would
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    def install(self) -> "FlightRecorder":
        """Arm every dump trigger (idempotent). Safe off the main
        thread — the SIGTERM hook is simply skipped there."""
        if self._installed:
            return self
        self._installed = True
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_exception
        self._prev_threading_hook = threading.excepthook
        threading.excepthook = self._on_thread_exception
        try:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm
            )
        except (ValueError, OSError):  # not the main thread
            self._prev_sigterm = None
        import atexit

        atexit.register(self._atexit)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._flush_loop, name="tnc-flight-recorder", daemon=True
        )
        self._thread.start()
        self.dump("armed")
        return self

    def _atexit(self) -> None:
        self._stop.set()
        self.dump("atexit")

    def uninstall(self) -> None:
        """Disarm (tests): stop the flush thread and restore hooks."""
        if not self._installed:
            return
        self._installed = False
        import atexit

        atexit.unregister(self._atexit)
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=self.flush_interval_s + 5.0)
        if sys.excepthook == self._on_exception:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if threading.excepthook == self._on_thread_exception:
            threading.excepthook = (
                self._prev_threading_hook or threading.__excepthook__
            )
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass


_FLIGHT: FlightRecorder | None = None
_FLIGHT_LOCK = threading.Lock()


def flight_recorder() -> FlightRecorder | None:
    """The armed process-wide recorder, if any."""
    return _FLIGHT


def maybe_flight_recorder() -> FlightRecorder | None:
    """Arm (once) the process-wide :class:`FlightRecorder` when
    ``TNC_TPU_FLIGHT_RECORDER`` names a directory; called from
    ``obs.refresh_from_env`` so setting the env var is the whole
    deployment story. ``TNC_TPU_FLIGHT_INTERVAL`` overrides the
    periodic-flush cadence (seconds)."""
    global _FLIGHT
    directory = os.environ.get("TNC_TPU_FLIGHT_RECORDER", "").strip()
    if not directory:
        return _FLIGHT
    with _FLIGHT_LOCK:
        if _FLIGHT is not None and str(_FLIGHT.directory) == directory:
            return _FLIGHT
        try:
            interval = float(
                os.environ.get("TNC_TPU_FLIGHT_INTERVAL", "1.0")
            )
        except ValueError:
            interval = 1.0
        try:
            _FLIGHT = FlightRecorder(
                directory, flush_interval_s=interval
            ).install()
        except OSError:
            logger.warning(
                "fleet: could not arm flight recorder at %s", directory,
                exc_info=True,
            )
            return None
    return _FLIGHT
