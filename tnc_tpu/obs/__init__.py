"""tnc_tpu.obs — env-gated pipeline tracing + metrics.

``TNC_TPU_TRACE`` gates everything: unset → every API here is a
near-zero-cost no-op; ``1`` → spans/counters record in-process;
``TNC_TPU_TRACE=<path>.json`` → additionally auto-export a
Chrome-trace/Perfetto timeline at interpreter exit. See
``docs/observability.md``.
"""

from tnc_tpu.obs.core import (  # noqa: F401
    MetricsRegistry,
    NULL_SPAN,
    Span,
    SpanRecord,
    configure,
    counter_add,
    counters_by_prefix,
    enabled,
    gauge_set,
    get_registry,
    maybe_jax_profiler_trace,
    observe,
    refresh_from_env,
    reset,
    span,
    step_timing_enabled,
    trace_path,
    traced,
)
from tnc_tpu.obs.export import (  # noqa: F401
    chrome_trace_events,
    emit_metrics,
    export_chrome_trace,
    export_jsonl,
    format_summary_table,
    load_trace_events,
    trace_summary,
)
from tnc_tpu.obs.calibrate import (  # noqa: F401
    CalibratedCostModel,
    DeviceModel,
    StepSample,
    calibration_report,
    fit_device_model,
    step_samples,
)
