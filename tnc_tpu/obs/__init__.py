"""tnc_tpu.obs — env-gated pipeline tracing + metrics.

``TNC_TPU_TRACE`` gates everything: unset → every API here is a
near-zero-cost no-op; ``1`` → spans/counters record in-process;
``TNC_TPU_TRACE=<path>.json`` → additionally auto-export a
Chrome-trace/Perfetto timeline at interpreter exit. See
``docs/observability.md``.
"""

from tnc_tpu.obs.core import (  # noqa: F401
    MetricsRegistry,
    NULL_SPAN,
    QuantileSummary,
    Span,
    SpanRecord,
    configure,
    counter_add,
    counters_by_prefix,
    enabled,
    gauge_set,
    get_registry,
    maybe_jax_profiler_trace,
    observe,
    process_trace_path,
    refresh_from_env,
    reset,
    span,
    step_timing_enabled,
    trace_args,
    trace_path,
    traced,
)
from tnc_tpu.obs.export import (  # noqa: F401
    chrome_trace_events,
    emit_metrics,
    export_chrome_trace,
    export_jsonl,
    format_serve_rollup,
    format_summary_table,
    load_trace_events,
    merge_trace_files,
    serve_trace_rollup,
    trace_summary,
)
from tnc_tpu.obs.calibrate import (  # noqa: F401
    CalibratedCostModel,
    DeviceModel,
    StepSample,
    calibration_report,
    fit_device_model,
    step_samples,
)
from tnc_tpu.obs.slo import (  # noqa: F401
    BurnWindow,
    DriftDetector,
    LatencyObjective,
    SLOConfig,
    SLOEngine,
)
from tnc_tpu.obs.cost_truth import (  # noqa: F401
    CostTruth,
    CostTruthConfig,
    ModelRegistry,
    ModelRegistryWatcher,
    PlanScoreboard,
    ProductionSampler,
    refit_model,
)
# the HTTP endpoint layer re-exports lazily (PEP 562): `from tnc_tpu
# import obs` happens in every module of the library, and only
# telemetry-serving processes should pay the http.server import
_HTTP_EXPORTS = (
    "TelemetryServer",
    "parse_prometheus",
    "parse_prometheus_types",
    "render_prometheus",
)

# the fleet plane (cross-host trace propagation, replica registry,
# federation, flight recorder) re-exports lazily for the same reason
_FLEET_EXPORTS = (
    "FleetAggregator",
    "FleetRegistry",
    "FlightRecorder",
    "Heartbeat",
    "TraceContext",
    "adopt_trace_context",
    "current_dispatch_context",
    "dispatch_context",
    "flight_annotations",
    "flight_recorder",
    "maybe_flight_recorder",
    "merge_fleet_metrics",
    "replica_identity",
    "replica_name",
    "set_flight_annotation",
)


def __getattr__(name: str):
    if name in _HTTP_EXPORTS:
        from tnc_tpu.obs import http as _http

        return getattr(_http, name)
    if name in _FLEET_EXPORTS:
        from tnc_tpu.obs import fleet as _fleet

        return getattr(_fleet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
