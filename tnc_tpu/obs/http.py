"""Live telemetry endpoint: Prometheus ``/metrics`` + ``/healthz`` +
``/slo`` over the stdlib HTTP server.

The obs registry answers "what happened" in-process; this module makes
the answer scrapeable while the process serves. Design constraints:

- **stdlib only** (``http.server`` on a daemon thread) — a serving
  replica must not grow a web-framework dependency;
- **deterministic text**: families sorted by name, series sorted by
  label set, one ``# TYPE`` line per family — two scrapes of the same
  state are byte-identical, and the rendering is testable as a string;
- **correct escaping**: label values escape ``\\``, ``"`` and newlines
  per the Prometheus text exposition format (v0.0.4);
- **provider hooks**, not imports: the server takes callables for
  health / SLO / extra metric families, so ``tnc_tpu.serve`` wires a
  live :class:`~tnc_tpu.serve.service.ContractionService` in without
  this module importing the serving layer.

Registry histograms render as Prometheus *summaries* (quantile series +
``_count`` + ``_sum``) straight off the same
:class:`~tnc_tpu.obs.core.QuantileSummary` objects ``stats()`` reads —
identical percentiles on both surfaces by construction.

>>> from tnc_tpu.obs.core import MetricsRegistry
>>> reg = MetricsRegistry()
>>> reg.counter_add("serve.requests", 3, outcome="completed")
>>> text = render_prometheus(reg)
>>> print(text.splitlines()[0])
# TYPE tnc_tpu_serve_requests_total counter
>>> print(text.splitlines()[1])
tnc_tpu_serve_requests_total{outcome="completed"} 3.0
"""

from __future__ import annotations

import http.server
import json
import logging
import re
import socket
import threading
from typing import Callable, Iterable

from tnc_tpu.obs.core import MetricsRegistry, get_registry

logger = logging.getLogger(__name__)

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "tnc_tpu_"

#: an extra metric sample a provider hands the renderer:
#: ``(family_type, family_name, labels_dict, value)`` with
#: ``family_type`` in {"counter", "gauge", "summary"}
Sample = tuple


def metric_name(name: str, prefix: str = _PREFIX) -> str:
    """Registry metric name → Prometheus family name (dots become
    underscores, everything namespaced under ``tnc_tpu_``).

    >>> metric_name("serve.plan_cache.hit")
    'tnc_tpu_serve_plan_cache_hit'
    """
    name = _NAME_BAD.sub("_", name)
    if not name.startswith(prefix):
        name = prefix + name
    if name[0].isdigit():  # family names may not start with a digit
        name = "_" + name
    return name


def escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double quote,
    newline.

    >>> escape_label_value('a"b\\\\c\\nd')
    'a\\\\"b\\\\\\\\c\\\\nd'
    """
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_labels(labels) -> str:
    """Sorted, escaped ``{k="v",...}`` label block ('' when empty).
    Accepts a dict or the registry's ``((k, v), ...)`` tuple form."""
    items = sorted(dict(labels).items()) if labels else []
    if not items:
        return ""
    inner = ",".join(
        f'{_NAME_BAD.sub("_", str(k))}="{escape_label_value(v)}"'
        for k, v in items
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    return repr(float(v))


def render_prometheus(
    registry: MetricsRegistry | None = None,
    extra: Iterable[Sample] = (),
    base_labels: dict | None = None,
) -> str:
    """Render a registry (+ provider samples) as Prometheus text
    exposition format v0.0.4. Counters gain the conventional ``_total``
    suffix; histograms render as summaries with ``quantile`` series.
    Output ordering is deterministic: families by name, series by label
    block. ``base_labels`` are merged into EVERY series (series labels
    win) — how a fleet replica stamps ``replica=`` onto its whole
    endpoint."""
    reg = registry if registry is not None else get_registry()
    # family name -> (type, {label_block: value}); keyed by label block
    # so a provider sample OVERRIDES a registry series with the same
    # family + labels (e.g. the service's live queue-depth gauge vs the
    # traced `serve.queue_depth` gauge) instead of emitting a duplicate
    # sample, which a Prometheus server rejects as a parse error
    families: dict[str, tuple[str, dict[str, float]]] = {}

    def add(ftype: str, fname: str, labels, value: float) -> None:
        if base_labels:
            labels = {**base_labels, **dict(labels or {})}
        fam = families.setdefault(fname, (ftype, {}))
        if fam[0] != ftype:
            # same family name claimed by two metric types: keep the
            # first, suffix the newcomer so the exposition stays valid
            return add(ftype, f"{fname}_{ftype}", labels, value)
        fam[1][format_labels(labels)] = float(value)

    for (name, labels), value in reg.counters().items():
        add("counter", metric_name(name) + "_total", labels, value)
    for (name, labels), value in reg.gauges().items():
        add("gauge", metric_name(name), labels, value)
    # histograms() snapshots each summary UNDER the registry lock, so a
    # scrape mid-observe still renders an internally consistent block
    for (name, labels), snap in reg.histograms().items():
        fname = metric_name(name)
        base = dict(labels)
        for key, v in snap.items():
            if key.startswith("p"):  # p50 / p90 / p99 / p99_9 ...
                q = float(key[1:].replace("_", ".")) / 100.0
                add("summary", fname, {**base, "quantile": f"{q:g}"}, v)
        add("summary", fname + "_count", base, snap["count"])
        add("summary", fname + "_sum", base, snap["sum"])
    for ftype, fname, labels, value in extra:
        fname = metric_name(str(fname))
        # provider counters get the same conventional suffix as
        # registry counters — one naming rule on the whole endpoint
        if ftype == "counter" and not fname.endswith("_total"):
            fname += "_total"
        add(str(ftype), fname, labels, value)

    lines: list[str] = []
    for fname in sorted(families):
        ftype, series = families[fname]
        # summary auxiliary series (_count/_sum) ride their parent's
        # TYPE line in real exporters; standalone is simplest and valid
        lines.append(f"# TYPE {fname} {ftype}")
        for label_block, value in sorted(series.items()):
            lines.append(f"{fname}{label_block} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict[str, float]:
    """Inverse of :func:`render_prometheus` for tests and the ops CLI:
    ``{'name{label="v"}': value}`` (comment lines skipped).

    >>> parse_prometheus('# TYPE a counter\\na{x="1"} 2.0\\n')
    {'a{x="1"}': 2.0}
    """
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out


def parse_prometheus_types(text: str) -> dict[str, str]:
    """Family-name → type map from the ``# TYPE`` lines — the half of
    the exposition :func:`parse_prometheus` drops, needed by the fleet
    aggregator to tell summed-across-replicas counters from
    kept-per-replica gauges/summaries.

    >>> parse_prometheus_types('# TYPE a counter\\na 1.0\\n')
    {'a': 'counter'}
    """
    out: dict[str, str] = {}
    for line in text.splitlines():
        parts = line.strip().split()
        if len(parts) == 4 and parts[0] == "#" and parts[1] == "TYPE":
            out[parts[2]] = parts[3]
    return out


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "tnc-tpu-telemetry/1.0"
    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 — stdlib naming
        srv: "TelemetryServer" = self.server.telemetry  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = srv.render_metrics().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = 200
            elif path == "/healthz":
                health = srv.health()
                body = json.dumps(health).encode("utf-8")
                ctype = "application/json"
                status = 200 if health.get("status") == "ok" else 503
            elif path == "/slo":
                body = json.dumps(srv.slo()).encode("utf-8")
                ctype = "application/json"
                status = 200
            elif path == "/fleet":
                body = json.dumps(srv.fleet()).encode("utf-8")
                ctype = "application/json"
                status = 200
            elif path == "/calibration":
                body = json.dumps(srv.calibration()).encode("utf-8")
                ctype = "application/json"
                status = 200
            else:
                body = b'{"error": "not found"}'
                ctype = "application/json"
                status = 404
        except Exception as exc:  # noqa: BLE001 — a scrape must not kill serving
            logger.exception("telemetry handler failed for %s", path)
            body = json.dumps({"error": str(exc)}).encode("utf-8")
            ctype = "application/json"
            status = 500
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args) -> None:  # silence stderr
        logger.debug("telemetry: " + fmt, *args)


class TelemetryServer:
    """Own one scrape endpoint for a serving process.

    ``port=0`` binds an ephemeral port (read it back from ``.port``
    after :meth:`start`). Provider hooks:

    - ``extra_metrics_fn() -> iterable[Sample]`` — service-level
      families merged into ``/metrics`` next to the obs registry;
    - ``health_fn() -> dict`` — the ``/healthz`` body (``status`` key;
      anything but ``"ok"`` answers 503);
    - ``slo_fn() -> dict`` — the ``/slo`` JSON body;
    - ``fleet_fn() -> dict`` — the ``/fleet`` JSON body (the federated
      cross-replica view, usually a
      :meth:`~tnc_tpu.obs.fleet.FleetAggregator.snapshot`);
    - ``calibration_fn() -> dict`` — the ``/calibration`` JSON body
      (the cost-truth loop's state: live model generation, sampler
      fill, refit ledger, plan scoreboard; see
      :mod:`tnc_tpu.obs.cost_truth`).

    ``base_labels`` stamps every ``/metrics`` series (fleet replicas
    pass ``{"replica": "p<idx>"}`` so scrapes stay distinguishable
    after federation).

    :meth:`stop` shuts the listener down and **releases the port**
    (pinned by ``tests/test_slo.py::test_endpoint_port_release``).

    >>> srv = TelemetryServer(registry=MetricsRegistry()).start()
    >>> import urllib.request
    >>> with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
    ...     json.load(r)["status"]
    'ok'
    >>> srv.stop()
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        health_fn: Callable[[], dict] | None = None,
        slo_fn: Callable[[], dict] | None = None,
        extra_metrics_fn: Callable[[], Iterable[Sample]] | None = None,
        fleet_fn: Callable[[], dict] | None = None,
        base_labels: dict | None = None,
        calibration_fn: Callable[[], dict] | None = None,
    ):
        self.registry = registry
        self.host = host
        self._requested_port = int(port)
        self.health_fn = health_fn
        self.slo_fn = slo_fn
        self.extra_metrics_fn = extra_metrics_fn
        self.fleet_fn = fleet_fn
        self.calibration_fn = calibration_fn
        self.base_labels = dict(base_labels) if base_labels else None
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- provider plumbing ----------------------------------------------

    def render_metrics(self) -> str:
        extra = list(self.extra_metrics_fn()) if self.extra_metrics_fn else []
        return render_prometheus(
            self.registry if self.registry is not None else get_registry(),
            extra,
            base_labels=self.base_labels,
        )

    def health(self) -> dict:
        return self.health_fn() if self.health_fn else {"status": "ok"}

    def slo(self) -> dict:
        return self.slo_fn() if self.slo_fn else {}

    def fleet(self) -> dict:
        return self.fleet_fn() if self.fleet_fn else {"enabled": False}

    def calibration(self) -> dict:
        return (
            self.calibration_fn() if self.calibration_fn
            else {"enabled": False}
        )

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> int:
        return (
            self._httpd.server_address[1]
            if self._httpd is not None
            else self._requested_port
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        httpd = http.server.ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler
        )
        httpd.telemetry = self  # type: ignore[attr-defined]
        httpd.daemon_threads = True
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name="tnc-telemetry",
            daemon=True,
            kwargs={"poll_interval": 0.1},
        )
        self._thread.start()
        logger.info("telemetry endpoint listening on %s", self.url)
        return self

    def stop(self) -> None:
        """Stop serving and release the port (idempotent)."""
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        # server_close() releases the listening socket; SO_REUSEADDR on
        # the stdlib server means the port is immediately rebindable
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=10.0)

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def wait_port_released(host: str, port: int, timeout_s: float = 5.0) -> bool:
    """True once nothing accepts connections on ``host:port`` (the
    endpoint-lifecycle test's probe)."""
    import time as _time

    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=0.2):
                pass
        except OSError:
            return True
        _time.sleep(0.05)
    return False
