"""Exporters for the obs registry: Chrome-trace/Perfetto JSON, JSONL,
the benchmark JSON log sink, and the per-stage summary table.

The Chrome trace format is the least-common-denominator timeline schema
(``ui.perfetto.dev`` and ``chrome://tracing`` both load it): a
``traceEvents`` list where every slice is a balanced ``B``/``E`` pair
carrying ``name``/``ts``/``pid``/``tid`` (timestamps in microseconds).
One exported file renders the whole pipeline — planning, partitioning,
slicing, hoisted prelude vs per-slice residual, chunked dispatches, SPMD
shard phases, fan-in — as one timeline.

>>> import tnc_tpu.obs as obs
>>> from tnc_tpu.obs.core import MetricsRegistry
>>> reg = obs.configure(enabled=True, registry=MetricsRegistry())
>>> with obs.span("sliced.prelude") as sp:
...     _ = sp.add(flops=64)
>>> events = chrome_trace_events(reg)
>>> [e["ph"] for e in events if e["name"] == "sliced.prelude"]
['B', 'E']
>>> rows = trace_summary(events)
>>> rows[0]["name"], rows[0]["count"], rows[0]["flops"]
('sliced.prelude', 1, 64.0)
>>> _ = obs.configure(enabled=False)
"""

from __future__ import annotations

import json
import logging
from typing import Any, Iterable

from tnc_tpu.obs.core import MetricsRegistry, get_registry

logger = logging.getLogger(__name__)


def _warn_if_truncated(reg: MetricsRegistry, sink: str) -> int:
    """Spans past the retention cap (``TNC_TPU_TRACE_MAX_SPANS``) are
    counted but dropped; every exporter surfaces that loudly — a
    truncated trace must never read as a complete one. Returns the
    dropped count."""
    dropped = reg.dropped_spans()
    if dropped:
        logger.warning(
            "obs: span retention cap hit — %d spans were dropped; the "
            "%s export is PARTIAL (raise TNC_TPU_TRACE_MAX_SPANS to "
            "keep more)",
            dropped,
            sink,
        )
    return dropped


def chrome_trace_events(
    registry: MetricsRegistry | None = None,
    include_open: bool = True,
) -> list[dict]:
    """Registry spans → Chrome-trace event dicts (``B``/``E`` pairs plus
    process/thread ``M`` metadata), sorted by timestamp."""
    reg = registry if registry is not None else get_registry()
    events: list[dict] = []
    threads: dict[tuple[int, int], str] = {}
    for rec in reg.span_records(include_open=include_open):
        threads.setdefault((rec.pid, rec.tid), rec.thread_name)
        ts = rec.start_ns / 1e3  # Chrome trace timestamps are in µs
        common = {"name": rec.name, "cat": rec.name.split(".", 1)[0],
                  "pid": rec.pid, "tid": rec.tid}
        args = {k: _jsonable(v) for k, v in rec.args.items()}
        args["depth"] = rec.depth
        events.append({**common, "ph": "B", "ts": ts, "args": args})
        events.append({**common, "ph": "E", "ts": ts + rec.dur_ns / 1e3})
    # B before E at equal ts (zero-duration spans) keeps pairs balanced
    events.sort(key=lambda e: (e["ts"], 0 if e["ph"] != "E" else 1))
    meta = _process_meta({pid for pid, _tid in threads}) + [
        {"name": "thread_name", "ph": "M", "ts": 0.0, "pid": pid, "tid": tid,
         "args": {"name": tname}}
        for (pid, tid), tname in sorted(threads.items())
    ]
    return meta + events


def _process_meta(pids: set[int]) -> list[dict]:
    """``process_name`` metadata events carrying this replica's fleet
    identity (process index / hostname / pid) — a merged multi-host
    timeline then names every process track after the replica that
    produced it."""
    label = None
    own_pid = None
    try:
        from tnc_tpu.obs.fleet import replica_identity, replica_name

        ident = replica_identity()
        own_pid = ident["pid"]
        label = f"{replica_name(ident)} {ident['host']} pid={own_pid}"
    except Exception:  # noqa: BLE001 — identity is best-effort metadata
        pass
    return [
        {"name": "process_name", "ph": "M", "ts": 0.0, "pid": pid, "tid": 0,
         "args": {"name": label if pid == own_pid and label else f"pid {pid}"}}
        for pid in sorted(pids)
    ]


def _jsonable(v: Any) -> Any:
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


def export_chrome_trace(
    path: str, registry: MetricsRegistry | None = None
) -> str:
    """Write the registry as a Chrome-trace JSON file loadable in
    ``ui.perfetto.dev``; counters/gauges ride along under ``otherData``
    (including ``dropped_spans``, warned about when nonzero). Returns
    ``path``."""
    reg = registry if registry is not None else get_registry()
    _warn_if_truncated(reg, "Chrome-trace")
    other = reg.snapshot()
    # fleet-merge anchors: the wall-clock twin of the span epoch places
    # this file on a cross-process timeline; the replica identity names
    # which host/process produced it
    other["epoch_unix_ns"] = getattr(reg, "epoch_unix_ns", None)
    try:
        from tnc_tpu.obs.fleet import replica_identity

        other["replica"] = replica_identity()
    except Exception:  # noqa: BLE001 — identity is best-effort metadata
        pass
    doc = {
        "traceEvents": chrome_trace_events(reg),
        "displayTimeUnit": "ms",
        "otherData": other,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return path


def export_jsonl(path: str, registry: MetricsRegistry | None = None) -> str:
    """Write every span and metric as one JSON object per line (the
    flexi_logger-style record stream; round-trips through
    ``json.loads`` per line), histograms included, closing with a
    ``dropped_spans`` record so a capped trace is never silently
    partial. Returns ``path``."""
    reg = registry if registry is not None else get_registry()
    dropped = _warn_if_truncated(reg, "JSONL")
    with open(path, "w", encoding="utf-8") as fh:
        for rec in reg.span_records():
            fh.write(json.dumps({
                "type": "span", "name": rec.name,
                "start_s": rec.start_ns / 1e9, "dur_s": rec.dur_ns / 1e9,
                "pid": rec.pid, "tid": rec.tid, "depth": rec.depth,
                "args": {k: _jsonable(v) for k, v in rec.args.items()},
            }) + "\n")
        snap = reg.snapshot()
        for kind in ("counters", "gauges"):
            for name, value in snap[kind].items():
                fh.write(json.dumps(
                    {"type": kind[:-1], "name": name, "value": value}
                ) + "\n")
        for name, h in snap["histograms"].items():
            fh.write(json.dumps(
                {"type": "histogram", "name": name, **h}
            ) + "\n")
        fh.write(json.dumps(
            {"type": "dropped_spans", "value": dropped}
        ) + "\n")
    return path


def emit_metrics(
    logger: logging.Logger | None = None,
    registry: MetricsRegistry | None = None,
) -> int:
    """Log every metric — counters, gauges, histograms, span stats — as
    a structured record through the std logging tree, so
    :class:`tnc_tpu.benchmark.logging_util.JsonFormatter` (which
    serializes ``extra=`` fields) lands them in the per-process JSONL
    sink. A ``dropped_spans`` record (warned about when nonzero) closes
    the stream. Returns the number of records emitted."""
    reg = registry if registry is not None else get_registry()
    lg = logger if logger is not None else logging.getLogger("tnc_tpu.obs")
    dropped = _warn_if_truncated(reg, "metrics")
    n = 0
    snap = reg.snapshot()
    for kind in ("counters", "gauges"):
        for name, value in snap[kind].items():
            lg.info(
                "metric", extra={"metric_type": kind[:-1], "metric": name,
                                 "value": value},
            )
            n += 1
    for name, h in snap["histograms"].items():
        lg.info(
            "metric", extra={"metric_type": "histogram", "metric": name, **h},
        )
        n += 1
    for name, stats in reg.span_stats().items():
        lg.info(
            "metric", extra={"metric_type": "span", "metric": name, **stats},
        )
        n += 1
    lg.info(
        "metric",
        extra={
            "metric_type": "dropped_spans",
            "metric": "dropped_spans",
            "value": dropped,
        },
    )
    n += 1
    return n


def load_trace_events(path: str) -> list[dict]:
    """Read back a Chrome-trace JSON (either the ``{"traceEvents": []}``
    object or a bare event array)."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return doc["traceEvents"] if isinstance(doc, dict) else doc


def merge_trace_files(paths: Iterable[str]) -> dict:
    """Merge per-process Chrome-trace exports into ONE fleet timeline.

    Span timestamps are perf-counter-relative to each process's own
    registry epoch; every export since the fleet plane also carries the
    wall-clock twin of that epoch (``otherData.epoch_unix_ns``), so the
    merge shifts each file onto the earliest epoch and re-sorts. Files
    without the anchor (pre-fleet exports) merge unshifted — their
    spans still aggregate correctly, they just don't align in time.

    Returns ``{"events": [...], "replicas": [{path, replica,
    shift_ms}, ...]}`` — feed ``events`` to :func:`trace_summary` /
    :func:`serve_trace_rollup` for the cross-host view (the ``--fleet``
    mode of ``scripts/trace_summarize.py``).
    """
    docs: list[tuple[str, dict]] = []
    for path in sorted(str(p) for p in paths):
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        if not isinstance(doc, dict):
            doc = {"traceEvents": doc, "otherData": {}}
        docs.append((path, doc))
    epochs = [
        (doc.get("otherData") or {}).get("epoch_unix_ns")
        for _path, doc in docs
    ]
    known = [e for e in epochs if e]
    base = min(known) if known else None
    events: list[dict] = []
    replicas: list[dict] = []
    for (path, doc), epoch in zip(docs, epochs):
        shift_us = (epoch - base) / 1e3 if (epoch and base) else 0.0
        for ev in doc.get("traceEvents", []):
            if shift_us and ev.get("ph") in ("B", "E"):
                ev = {**ev, "ts": ev["ts"] + shift_us}
            events.append(ev)
        replicas.append({
            "path": path,
            "replica": (doc.get("otherData") or {}).get("replica"),
            "shift_ms": shift_us / 1e3,
            "aligned": bool(epoch and base),
        })
    # metadata events (ts 0) first, then the same B-before-E tie-break
    # the per-process exporter uses; the sort is stable, so each file's
    # internal order survives ties and B/E pairs stay balanced per
    # (pid, tid)
    events.sort(key=lambda e: (
        0 if e.get("ph") == "M" else 1,
        e.get("ts", 0.0),
        0 if e.get("ph") != "E" else 1,
    ))
    return {"events": events, "replicas": replicas}


def trace_summary(events: Iterable[dict]) -> list[dict]:
    """Per-stage aggregate over Chrome-trace events: for every span name,
    the call count, total wall time, and the summed numeric counters the
    spans carried (flops, bytes, slices, ...). Rows are sorted by total
    time, descending. Only top-level occurrences of a name are summed
    when the same name nests inside itself."""
    open_spans: dict[tuple[int, int], list[tuple[str, float, dict]]] = {}
    agg: dict[str, dict] = {}
    for ev in events:
        ph = ev.get("ph")
        if ph not in ("B", "E"):
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        stack = open_spans.setdefault(key, [])
        if ph == "B":
            stack.append((ev["name"], ev["ts"], ev.get("args", {})))
            continue
        if not stack or stack[-1][0] != ev["name"]:  # unbalanced: skip
            continue
        name, ts0, args = stack.pop()
        if any(frame[0] == name for frame in stack):
            continue  # self-nested: the outer occurrence will count it
        row = agg.setdefault(
            name, {"name": name, "count": 0, "total_ms": 0.0}
        )
        row["count"] += 1
        row["total_ms"] += (ev["ts"] - ts0) / 1e3
        for k, v in args.items():
            if k != "depth" and isinstance(v, (int, float)):
                row[k] = row.get(k, 0.0) + float(v)
    return sorted(agg.values(), key=lambda r: -r["total_ms"])


def _completed_spans(events: Iterable[dict]) -> list[dict]:
    """Balanced ``B``/``E`` pairs → ``[{name, dur_ms, args}]``. A
    sibling of :func:`trace_summary`'s pairing walk, kept separate
    because that one needs the live stack for its self-nesting rule —
    keep the unbalanced-span handling of the two in agreement."""
    open_spans: dict[tuple[int, int], list[tuple[str, float, dict]]] = {}
    out: list[dict] = []
    for ev in events:
        ph = ev.get("ph")
        if ph not in ("B", "E"):
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        stack = open_spans.setdefault(key, [])
        if ph == "B":
            stack.append((ev["name"], ev["ts"], ev.get("args", {})))
            continue
        if not stack or stack[-1][0] != ev["name"]:  # unbalanced: skip
            continue
        name, ts0, args = stack.pop()
        out.append(
            {"name": name, "dur_ms": (ev["ts"] - ts0) / 1e3, "args": args}
        )
    return out


def serve_trace_rollup(events: Iterable[dict]) -> dict:
    """Roll ``serve.*`` spans up per request id and per query type.

    Two span families feed it (``tnc_tpu.serve.service``):

    - ``serve.request`` — one terminal span per request whose args ARE
      the request timeline (rid, type, outcome, queue_age_s,
      batch_wait_s, dispatch_s, riders, generation);
    - ``serve.dispatch`` — one span per batched execution, its wall
      time shared by the ``riders`` id list it carries; the rollup
      attributes ``dur / len(riders)`` to each rider, so shared batch
      time lands on requests and query types without double counting.

    Returns ``{"requests": {rid: {...}}, "by_type": {kind: {...}},
    "dispatch_wall_ms", "attributed_ms", "attributed_share"}`` —
    ``attributed_share`` is the CI pin: the fraction of total dispatch
    wall time the rider lists account for (≥ 0.95 on a healthy trace).
    """
    requests: dict[str, dict] = {}
    by_type: dict[str, dict] = {}
    dispatch_wall = 0.0
    attributed = 0.0
    spans = _completed_spans(events)
    # two passes: request rows first, THEN dispatch attribution — a
    # request's serve.request span always closes after the dispatch
    # span that served it, so a single in-order pass would attribute
    # into rows that don't exist yet
    for span in spans:
        args = span["args"]
        if span["name"] == "serve.request":
            rid = str(args.get("rid", "?"))
            requests[rid] = {
                "type": args.get("type", "?"),
                "outcome": args.get("outcome", "?"),
                "latency_s": float(args.get("latency_s", 0.0) or 0.0),
                "queue_age_s": float(args.get("queue_age_s", 0.0) or 0.0),
                "batch_wait_s": float(args.get("batch_wait_s", 0.0) or 0.0),
                "dispatch_s": float(args.get("dispatch_s", 0.0) or 0.0),
                "riders": int(args.get("riders", 1) or 1),
                "generation": int(args.get("generation", 0) or 0),
                "attributed_ms": 0.0,
            }
    for span in spans:
        args = span["args"]
        if span["name"] == "serve.dispatch":
            dispatch_wall += span["dur_ms"]
            riders = [
                r for r in str(args.get("riders", "")).split(",") if r
            ]
            if not riders:
                continue
            share = span["dur_ms"] / len(riders)
            attributed += span["dur_ms"]
            kind = str(args.get("kind", "?"))
            row = by_type.setdefault(
                kind,
                {"dispatches": 0, "dispatch_ms": 0.0, "requests": 0},
            )
            row["dispatches"] += 1
            row["dispatch_ms"] += span["dur_ms"]
            for rid in riders:
                req = requests.get(rid)
                if req is not None:
                    req["attributed_ms"] += share
    for req in requests.values():
        row = by_type.setdefault(
            req["type"],
            {"dispatches": 0, "dispatch_ms": 0.0, "requests": 0},
        )
        row["requests"] += 1
        for fld in ("latency_s", "queue_age_s", "batch_wait_s", "dispatch_s"):
            row[f"{fld}_sum"] = row.get(f"{fld}_sum", 0.0) + req[fld]
    for row in by_type.values():
        n = max(row["requests"], 1)
        for fld in ("latency_s", "queue_age_s", "batch_wait_s", "dispatch_s"):
            row[f"{fld}_mean"] = row.pop(f"{fld}_sum", 0.0) / n
    return {
        "requests": requests,
        "by_type": by_type,
        "dispatch_wall_ms": dispatch_wall,
        "attributed_ms": attributed,
        "attributed_share": (
            attributed / dispatch_wall if dispatch_wall > 0 else 0.0
        ),
    }


def format_serve_rollup(rollup: dict) -> str:
    """Aligned text rendering of :func:`serve_trace_rollup` (the
    ``trace_summarize.py --serve`` output): one row per query type,
    then the attribution line."""
    head = (
        f"{'query type':<14} {'reqs':>6} {'dispatches':>11} "
        f"{'q-age ms':>9} {'wait ms':>9} {'disp ms':>9} {'lat ms':>9}"
    )
    lines = [head, "-" * len(head)]
    for kind in sorted(rollup["by_type"]):
        row = rollup["by_type"][kind]
        lines.append(
            f"{kind:<14} {row['requests']:>6} {row['dispatches']:>11} "
            f"{row.get('queue_age_s_mean', 0.0) * 1e3:>9.2f} "
            f"{row.get('batch_wait_s_mean', 0.0) * 1e3:>9.2f} "
            f"{row.get('dispatch_s_mean', 0.0) * 1e3:>9.2f} "
            f"{row.get('latency_s_mean', 0.0) * 1e3:>9.2f}"
        )
    lines.append(
        f"{len(rollup['requests'])} requests; dispatch wall "
        f"{rollup['dispatch_wall_ms']:.2f} ms, "
        f"{rollup['attributed_share']:.1%} attributed to request ids"
    )
    return "\n".join(lines)


def format_summary_table(rows: list[dict]) -> str:
    """Render :func:`trace_summary` rows as an aligned text table with a
    time-share column (used by ``scripts/trace_summarize.py`` and the
    bench driver's stderr report)."""
    total = sum(r["total_ms"] for r in rows) or 1.0
    extra_cols: list[str] = []
    for r in rows:
        for k in r:
            if k not in ("name", "count", "total_ms") and k not in extra_cols:
                extra_cols.append(k)
    head = (
        f"{'stage':<36} {'count':>7} {'total_ms':>12} {'share':>7}"
        + "".join(f" {c:>12}" for c in extra_cols)
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        line = (
            f"{r['name']:<36} {r['count']:>7} {r['total_ms']:>12.2f} "
            f"{r['total_ms'] / total:>6.1%}"
        )
        for c in extra_cols:
            v = r.get(c)
            line += f" {v:>12.3g}" if isinstance(v, (int, float)) else " " * 13
        lines.append(line)
    return "\n".join(lines)
