"""Env-gated tracing + metrics core: spans, counters, gauges, histograms.

The reference logs structured records at every pipeline stage (compile,
partition, scatter, contract, fan-in — ``benchmark/src/utils.rs``,
``mpi/communication.rs:132``); this module is the reproduction's
equivalent answer to "where did the time/flops/bytes go", designed for
the TPU pipeline:

- :func:`span` — a context manager recording wall time, nesting depth,
  process and thread id, and attached counters for one pipeline stage
  (``with obs.span("compile", steps=254): ...``). Completed spans land
  in the process-local :class:`MetricsRegistry` and export as a
  Chrome-trace/Perfetto timeline (:mod:`tnc_tpu.obs.export`).
- :func:`counter_add` / :func:`gauge_set` / :func:`observe` — named
  metrics with optional labels, aggregated in the same registry.

Everything is **disabled unless ``TNC_TPU_TRACE`` is set** (or
:func:`configure` is called): the disabled fast path is one module-level
bool check and returns a shared no-op span, so instrumented executors
pay nothing measurable in production runs (pinned by
``tests/test_obs.py::test_disabled_span_overhead``).

``TNC_TPU_TRACE`` values: unset/``0`` → off; ``1``/``true`` → record
in-process; any other value → record *and* auto-export a Chrome-trace
JSON to that path at interpreter exit. ``TNC_TPU_TRACE_JAX=<dir>``
additionally wraps the distributed executors in ``jax.profiler.trace``
(:func:`maybe_jax_profiler_trace`).

>>> import tnc_tpu.obs as obs
>>> _ = obs.configure(enabled=True, registry=MetricsRegistry())
>>> with obs.span("compile", steps=3) as sp:
...     _ = sp.add(flops=100)
...     with obs.span("execute"):
...         pass
>>> recs = obs.get_registry().span_records()
>>> [(r.name, r.depth) for r in recs]
[('execute', 1), ('compile', 0)]
>>> obs.get_registry().counters()[('compile.flops', ())]
100.0
>>> _ = obs.configure(enabled=False)
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

_TRUTHY = ("1", "true", "yes", "on")

# Cap on retained span records: a runaway per-slice loop must not grow
# memory without bound; past the cap, spans are counted but dropped.
_MAX_SPANS_DEFAULT = 200_000


@dataclass(frozen=True)
class SpanRecord:
    """One completed (or still-open at export time) span."""

    name: str
    start_ns: int  # relative to the registry epoch
    dur_ns: int
    pid: int
    tid: int
    thread_name: str
    depth: int
    args: dict = field(default_factory=dict)


class _P2Quantile:
    """One streaming quantile via the P² algorithm (Jain & Chlamtac
    1985): five markers tracked in O(1) memory per observation — no
    retained samples. Below 5 observations the estimate is the exact
    nearest-rank percentile of what was seen."""

    __slots__ = ("p", "_q", "_n", "_np", "_dn", "_count")

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError("quantile must be in (0, 1)")
        self.p = float(p)
        self._q: list[float] = []  # marker heights (sorted samples < 5)
        self._n = [0.0, 1.0, 2.0, 3.0, 4.0]  # marker positions
        self._np = [0.0, 2 * p, 4 * p, 2 + 2 * p, 4.0]  # desired positions
        self._dn = [0.0, p / 2, p, (1 + p) / 2, 1.0]
        self._count = 0

    def observe(self, x: float) -> None:
        self._count += 1
        q = self._q
        if len(q) < 5:
            q.append(x)
            q.sort()
            return
        n = self._n
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            for i in range(1, 5):
                if x < q[i]:
                    k = i - 1
                    break
        for i in range(k + 1, 5):
            n[i] += 1.0
        for i in range(5):
            self._np[i] += self._dn[i]
        for i in (1, 2, 3):
            d = self._np[i] - n[i]
            if (d >= 1.0 and n[i + 1] - n[i] > 1.0) or (
                d <= -1.0 and n[i - 1] - n[i] < -1.0
            ):
                sign = 1 if d > 0 else -1
                cand = self._parabolic(i, sign)
                if not (q[i - 1] < cand < q[i + 1]):
                    cand = self._linear(i, sign)
                q[i] = cand
                n[i] += sign

    def _parabolic(self, i: int, d: int) -> float:
        q, n = self._q, self._n
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: int) -> float:
        q, n = self._q, self._n
        return q[i] + d * (q[i + d] - q[i]) / (n[i + d] - n[i])

    def value(self) -> float:
        if self._count == 0:
            return 0.0
        if self._count <= 5:
            s = self._q
            return float(s[min(len(s) - 1, int(self.p * (len(s) - 1)))])
        return float(self._q[2])


class QuantileSummary:
    """Bounded streaming distribution summary: count / sum / min / max
    plus P² estimates for a fixed quantile set — p50/p90/p99 without
    retaining raw samples, however long the stream runs. The shared
    percentile surface of :meth:`MetricsRegistry.observe`, the serving
    ``stats()`` latency blocks, and the ``/metrics`` Prometheus
    rendering — one object, identical numbers everywhere it is read.

    >>> s = QuantileSummary()
    >>> for v in range(1, 101):
    ...     s.observe(float(v))
    >>> snap = s.snapshot()
    >>> (snap["count"], snap["min"], snap["max"])
    (100, 1.0, 100.0)
    >>> 40.0 <= snap["p50"] <= 60.0
    True
    """

    QUANTILES = (0.5, 0.9, 0.99)
    __slots__ = ("count", "sum", "min", "max", "_estimators")

    def __init__(self, quantiles: tuple = QUANTILES):
        self.count = 0
        self.sum = 0.0
        self.min = 0.0
        self.max = 0.0
        self._estimators = {float(q): _P2Quantile(q) for q in quantiles}

    def observe(self, value: float) -> None:
        value = float(value)
        if self.count == 0:
            self.min = self.max = value
        else:
            self.min = min(self.min, value)
            self.max = max(self.max, value)
        self.count += 1
        self.sum += value
        for est in self._estimators.values():
            est.observe(value)

    def quantile(self, q: float) -> float:
        est = self._estimators.get(float(q))
        if est is None:
            raise KeyError(f"quantile {q} is not tracked")
        return est.value()

    def quantiles(self) -> dict[float, float]:
        return {q: est.value() for q, est in self._estimators.items()}

    def snapshot(self) -> dict:
        """Plain-data view; quantiles rendered as ``p50``-style keys."""
        out = {
            "count": self.count, "sum": self.sum,
            "min": self.min, "max": self.max,
        }
        for q, est in self._estimators.items():
            out[f"p{q * 100:g}".replace(".", "_")] = est.value()
        return out


class MetricsRegistry:
    """Process-local metric + span store. Thread-safe; one module-level
    instance serves the whole process (:func:`get_registry`), tests may
    swap in a fresh one via :func:`configure`.

    >>> reg = MetricsRegistry()
    >>> reg.counter_add("slices", 4)
    >>> reg.counter_add("slices", 2)
    >>> reg.counter_add("cache", 1, kind="hit")
    >>> reg.counters()[("slices", ())]
    6.0
    >>> reg.gauge_set("hbm_peak_bytes", 2.0**29)
    >>> reg.observe("step_ms", 1.5); reg.observe("step_ms", 2.5)
    >>> h = reg.histograms()[("step_ms", ())]
    >>> (h["count"], h["sum"], h["min"], h["max"])
    (2, 4.0, 1.5, 2.5)
    >>> sorted(k for k in h if k.startswith("p"))
    ['p50', 'p90', 'p99']
    """

    def __init__(self, max_spans: int | None = None) -> None:
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, QuantileSummary] = {}
        self._spans: list[SpanRecord] = []
        self._active: dict[int, "Span"] = {}
        self._dropped = 0
        if max_spans is None:
            max_spans = int(
                os.environ.get("TNC_TPU_TRACE_MAX_SPANS", _MAX_SPANS_DEFAULT)
            )
        self._max_spans = max_spans
        self.epoch_ns = time.perf_counter_ns()
        # wall-clock twin of the perf-counter epoch, captured at the
        # same instant: span timestamps are perf-counter-relative
        # (monotonic, per-process), so merging traces from DIFFERENT
        # processes needs this anchor to place them on one timeline
        # (tnc_tpu.obs.export.merge_trace_files)
        self.epoch_unix_ns = time.time_ns()

    # -- metrics ---------------------------------------------------------
    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (name, tuple(sorted(labels.items())))

    def counter_add(self, name: str, value: float = 1.0, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + float(value)

    def gauge_set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = QuantileSummary()
            h.observe(value)

    def counters(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._gauges)

    def histograms(self) -> dict[tuple, dict]:
        """Plain-data snapshots (taken under the lock, so each block is
        internally consistent) — also what the ``/metrics`` renderer
        reads."""
        with self._lock:
            return {k: v.snapshot() for k, v in self._hists.items()}

    # -- spans -----------------------------------------------------------
    def _span_opened(self, sp: "Span") -> None:
        with self._lock:
            self._active[id(sp)] = sp

    def _span_closed(self, sp: "Span", rec: SpanRecord) -> None:
        with self._lock:
            self._active.pop(id(sp), None)
            if len(self._spans) >= self._max_spans:
                self._dropped += 1
                return
            self._spans.append(rec)

    def span_records(self, include_open: bool = False) -> list[SpanRecord]:
        """Completed spans (chronological by end time). With
        ``include_open``, still-running spans are appended with their
        duration measured up to now — so a whole-run wrapper span shows
        up in a trace exported from inside it."""
        now = time.perf_counter_ns()
        with self._lock:
            recs = list(self._spans)
            if include_open:
                recs.extend(sp._record(now) for sp in self._active.values())
        return recs

    def recent_spans(
        self, n: int, include_open: bool = False
    ) -> list[SpanRecord]:
        """The last ``n`` completed spans (optionally with still-open
        spans appended) — an O(n) slice under the lock, NOT a copy of
        the whole store; the flight recorder polls this on a cadence."""
        now = time.perf_counter_ns()
        with self._lock:
            recs = self._spans[-max(int(n), 0):]
            if include_open:
                recs = recs + [
                    sp._record(now) for sp in self._active.values()
                ]
        return recs

    def dropped_spans(self) -> int:
        with self._lock:
            return self._dropped

    def span_stats(
        self, max_depth: int | None = None, tid: int | None = None
    ) -> dict[str, dict]:
        """Aggregate wall time per span name: ``{name: {count, total_s,
        min_s, max_s}}``. ``max_depth`` keeps only spans at or above a
        nesting level (``0`` = top-level phases only), so a per-phase
        breakdown does not double-count nested child spans. Depth is
        **per thread** (a worker-thread span starts at 0), so breakdowns
        over multi-threaded runs should also pin ``tid`` to the
        coordinating thread."""
        out: dict[str, dict] = {}
        for rec in self.span_records():
            if max_depth is not None and rec.depth > max_depth:
                continue
            if tid is not None and rec.tid != tid:
                continue
            s = out.get(rec.name)
            dur = rec.dur_ns / 1e9
            if s is None:
                out[rec.name] = {
                    "count": 1, "total_s": dur, "min_s": dur, "max_s": dur
                }
            else:
                s["count"] += 1
                s["total_s"] += dur
                s["min_s"] = min(s["min_s"], dur)
                s["max_s"] = max(s["max_s"], dur)
        return out

    def snapshot(self) -> dict:
        """Plain-data snapshot of every metric (JSON-ready; labels as
        ``name{k=v}`` strings)."""
        fmt = format_metric_key
        return {
            "counters": {fmt(k): v for k, v in self.counters().items()},
            "gauges": {fmt(k): v for k, v in self.gauges().items()},
            "histograms": {fmt(k): v for k, v in self.histograms().items()},
            "dropped_spans": self.dropped_spans(),
        }


def format_metric_key(key: tuple) -> str:
    """Registry metric key → ``name`` / ``name{k=v,...}`` string — the
    ONE rendering rule shared by :meth:`MetricsRegistry.snapshot` and
    :func:`counters_by_prefix` (they feed the same JSON consumers)."""
    name, labels = key
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class _NullSpan:
    """Shared no-op span: the whole disabled-path cost of ``with
    obs.span(...)`` is returning this singleton."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **args: Any) -> "_NullSpan":
        return self

    def add(self, **counters: Any) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()

_TLS = threading.local()


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


class _TraceArgsCtx:
    """Scope for :func:`trace_args`: while active, every span opened on
    this thread inherits the given args (explicit span args win)."""

    __slots__ = ("_args", "_prev")

    def __init__(self, args: dict):
        self._args = args

    def __enter__(self) -> "_TraceArgsCtx":
        self._prev = getattr(_TLS, "trace_extra", None)
        if self._args:
            merged = dict(self._prev) if self._prev else {}
            merged.update(self._args)
            _TLS.trace_extra = merged
        return self

    def __exit__(self, *exc: Any) -> bool:
        if self._args:
            _TLS.trace_extra = self._prev
        return False


def trace_args(**args: Any) -> _TraceArgsCtx:
    """Attach ambient args to every span this thread opens inside the
    context — the cross-host trace-propagation primitive: a
    ``serve_cluster`` worker adopts the root's request ids here so its
    ``partitioned.*`` / slice spans land in the merged fleet timeline
    already carrying them. Nesting merges (inner wins); explicit span
    args always win over ambient ones.

    >>> _ = configure(enabled=True, registry=MetricsRegistry())
    >>> with trace_args(riders="r1,r2"):
    ...     with span("partitioned.shard") as sp:
    ...         pass
    >>> get_registry().span_records()[-1].args["riders"]
    'r1,r2'
    >>> _ = configure(enabled=False)
    """
    return _TraceArgsCtx(args)


class Span:
    """A live span. Use via :func:`span`; not constructed directly."""

    __slots__ = ("name", "args", "_reg", "_start_ns", "_depth", "_tid",
                 "_tname")

    def __init__(self, name: str, registry: MetricsRegistry, args: dict):
        self.name = name
        self.args = args
        self._reg = registry

    def set(self, **args: Any) -> "Span":
        """Attach/overwrite span attributes (shown in the trace)."""
        self.args.update(args)
        return self

    def add(self, **counters: Any) -> "Span":
        """Accumulate numeric counters onto the span *and* the registry
        (as ``<span name>.<counter>``): flops, bytes moved, slices
        executed, cache hits, modeled HBM peaks..."""
        for key, value in counters.items():
            self.args[key] = self.args.get(key, 0) + value
            self._reg.counter_add(f"{self.name}.{key}", value)
        return self

    def __enter__(self) -> "Span":
        extra = getattr(_TLS, "trace_extra", None)
        if extra:
            self.args = {**extra, **self.args}
        st = _stack()
        self._depth = len(st)
        st.append(self)
        th = threading.current_thread()
        self._tid = th.ident or 0
        self._tname = th.name
        self._reg._span_opened(self)
        self._start_ns = time.perf_counter_ns()
        return self

    def _record(self, end_ns: int) -> SpanRecord:
        return SpanRecord(
            name=self.name,
            start_ns=self._start_ns - self._reg.epoch_ns,
            dur_ns=max(end_ns - self._start_ns, 0),
            pid=os.getpid(),
            tid=self._tid,
            thread_name=self._tname,
            depth=self._depth,
            args=dict(self.args),
        )

    def __exit__(self, *exc: Any) -> bool:
        end_ns = time.perf_counter_ns()
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # out-of-order exit: drop up to this span
            del st[st.index(self):]
        self._reg._span_closed(self, self._record(end_ns))
        return False


# -- module-level state + API ------------------------------------------

_ENABLED = False
_STEP_TIME = False
_TRACE_PATH: str | None = None
_REGISTRY = MetricsRegistry()
_ATEXIT_REGISTERED = False


def enabled() -> bool:
    """Is recording on? The one check every instrumented call site pays."""
    return _ENABLED


def step_timing_enabled() -> bool:
    """Is the opt-in per-step timing mode on (``TNC_TPU_STEP_TIME``)?

    When true *and* recording is on, the JAX backend's whole-program
    executor runs eagerly — one dispatch plus ``block_until_ready`` per
    :class:`~tnc_tpu.ops.program.PairStep` — so every step span carries
    a true measured wall time next to its predicted flops/bytes (the
    calibration input, :mod:`tnc_tpu.obs.calibrate`). The numpy oracle
    is synchronous anyway and records step spans whenever tracing is on.
    Off (the default): zero per-step sync, compiled dispatch unchanged.
    """
    return _STEP_TIME


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def trace_path() -> str | None:
    """Chrome-trace auto-export path (from ``TNC_TPU_TRACE=<path>`` or
    ``configure(trace_path=...)``), or None."""
    return _TRACE_PATH


def configure(
    enabled: bool | None = None,
    trace_path: str | None = None,
    registry: MetricsRegistry | None = None,
    step_time: bool | None = None,
) -> MetricsRegistry:
    """Programmatic override of the env gate (bench/tests). Returns the
    active registry. ``trace_path`` arms the atexit Chrome-trace export;
    ``step_time`` overrides the ``TNC_TPU_STEP_TIME`` per-step mode."""
    global _ENABLED, _STEP_TIME, _TRACE_PATH, _REGISTRY
    if registry is not None:
        _REGISTRY = registry
    if enabled is not None:
        _ENABLED = bool(enabled)
    if step_time is not None:
        _STEP_TIME = bool(step_time)
    if trace_path is not None:
        _TRACE_PATH = trace_path
        _register_atexit()
    return _REGISTRY


def reset() -> MetricsRegistry:
    """Swap in a fresh registry (keeps the enabled flag). For tests and
    for benchmarks that want a clean per-phase breakdown."""
    return configure(registry=MetricsRegistry())


def refresh_from_env() -> bool:
    """Re-read ``TNC_TPU_TRACE`` / ``TNC_TPU_STEP_TIME`` (import-time
    defaults; call after changing the env mid-process). Returns the new
    enabled state."""
    global _ENABLED, _STEP_TIME, _TRACE_PATH
    _STEP_TIME = (
        os.environ.get("TNC_TPU_STEP_TIME", "").strip().lower() in _TRUTHY
    )
    raw = os.environ.get("TNC_TPU_TRACE", "").strip()
    if not raw or raw == "0" or raw.lower() in ("false", "off", "no"):
        _ENABLED = False
        # the flight recorder needs span recording: arming it (env
        # TNC_TPU_FLIGHT_RECORDER) turns the registry back on
        _maybe_arm_flight_recorder()
        return _ENABLED
    _ENABLED = True
    if raw.lower() not in _TRUTHY:
        _TRACE_PATH = raw
        _register_atexit()
    _maybe_arm_flight_recorder()
    return True


def process_trace_path(
    path: str,
    process_index: int | None = None,
    process_count: int | None = None,
) -> str:
    """Per-process variant of a trace export path: in a multi-process
    fleet every replica suffixes its process index (``trace.json`` →
    ``trace.p1.json``) so a cluster run never clobbers its own export.
    Single-process runs (and runs without a distributed runtime) keep
    the path unchanged. Pass explicit index/count to override the
    ``jax.distributed`` probe (tests).

    >>> process_trace_path("/tmp/t.json", process_index=2,
    ...                    process_count=4)
    '/tmp/t.p2.json'
    >>> process_trace_path("/tmp/t.json", process_index=0,
    ...                    process_count=1)
    '/tmp/t.json'
    """
    if process_index is None or process_count is None:
        try:
            import jax

            process_count = int(jax.process_count())
            process_index = int(jax.process_index())
        except Exception:  # noqa: BLE001 — no jax / not initialized
            return path
    if process_count <= 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{process_index}{ext or '.json'}"


def _maybe_arm_flight_recorder() -> None:
    """Arm the crash flight recorder when ``TNC_TPU_FLIGHT_RECORDER``
    names a directory (lazy import — the fleet module only loads when
    the feature is on). The recorder needs span recording, so arming it
    also enables the registry."""
    global _ENABLED
    if not os.environ.get("TNC_TPU_FLIGHT_RECORDER", "").strip():
        return
    try:
        from tnc_tpu.obs import fleet as _fleet

        if _fleet.maybe_flight_recorder() is not None:
            _ENABLED = True
    except Exception:  # noqa: BLE001 — observability must not break import
        import logging

        logging.getLogger(__name__).warning(
            "obs: flight-recorder arming failed", exc_info=True
        )


def _register_atexit() -> None:
    global _ATEXIT_REGISTERED
    if _ATEXIT_REGISTERED:
        return
    _ATEXIT_REGISTERED = True
    import atexit

    def _dump() -> None:
        if _TRACE_PATH and (_REGISTRY.span_records() or _REGISTRY.counters()):
            from tnc_tpu.obs.export import export_chrome_trace

            try:
                # each replica of a fleet exports to its own
                # process-suffixed file (trace.json -> trace.p1.json)
                export_chrome_trace(
                    process_trace_path(_TRACE_PATH), _REGISTRY
                )
            except OSError:  # pragma: no cover - unwritable path at exit
                pass

    atexit.register(_dump)


def span(name: str, **args: Any):
    """Open a span for one pipeline stage. No-op singleton when disabled.

    Keyword arguments become span attributes; use :meth:`Span.add` for
    counters that should also aggregate process-wide."""
    if not _ENABLED:
        return NULL_SPAN
    return Span(name, _REGISTRY, args)


def traced(name: str, **static_args: Any):
    """Decorator form of :func:`span` for whole-function stages (the
    planning entry points). Disabled path: one bool check.

    >>> @traced("plan.demo", kind="test")
    ... def plan():
    ...     return 7
    >>> plan()   # disabled by default: plain call
    7
    """

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any):
            if not _ENABLED:
                return fn(*args, **kwargs)
            with Span(name, _REGISTRY, dict(static_args)):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def counter_add(name: str, value: float = 1.0, **labels) -> None:
    if _ENABLED:
        _REGISTRY.counter_add(name, value, **labels)


def gauge_set(name: str, value: float, **labels) -> None:
    if _ENABLED:
        _REGISTRY.gauge_set(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    if _ENABLED:
        _REGISTRY.observe(name, value, **labels)


def counters_by_prefix(prefix: str) -> dict[str, float]:
    """Flattened view of every counter under a name prefix, labels
    rendered as ``name{k=v}`` strings — how the bench record and tests
    read out a subsystem's activity (e.g. ``resilience.`` for retries,
    degradation rungs, checkpoint saves/resumes, fired faults).

    >>> _ = configure(enabled=True, registry=MetricsRegistry())
    >>> counter_add("resilience.retry.attempts", 2, site="spmd.dispatch")
    >>> counter_add("other.thing", 1)
    >>> counters_by_prefix("resilience.")
    {'resilience.retry.attempts{site=spmd.dispatch}': 2.0}
    >>> _ = configure(enabled=False, registry=MetricsRegistry())
    """
    out: dict[str, float] = {}
    for key, value in sorted(_REGISTRY.counters().items()):
        if key[0].startswith(prefix):
            out[format_metric_key(key)] = value
    return out


_JAX_TRACE_ACTIVE = False


class _JaxTraceCtx:
    """Context manager wrapping ``jax.profiler.trace`` when
    ``TNC_TPU_TRACE_JAX=<dir>`` is set; identity otherwise. Never nests
    (the profiler raises on reentry) and degrades to a no-op if the
    backend's profiler is unavailable (tunneled backends wedge —
    TPU_EVIDENCE_r04.md)."""

    __slots__ = ("_ctx",)

    def __enter__(self):
        global _JAX_TRACE_ACTIVE
        self._ctx = None
        trace_dir = os.environ.get("TNC_TPU_TRACE_JAX")
        if not trace_dir or _JAX_TRACE_ACTIVE:
            return self
        try:
            import jax

            self._ctx = jax.profiler.trace(trace_dir)
            self._ctx.__enter__()
            _JAX_TRACE_ACTIVE = True
        except Exception:  # noqa: BLE001 - profiler support is optional
            self._ctx = None
        return self

    def __exit__(self, *exc: Any) -> bool:
        global _JAX_TRACE_ACTIVE
        if self._ctx is not None:
            _JAX_TRACE_ACTIVE = False
            try:
                self._ctx.__exit__(*exc)
            except Exception:  # noqa: BLE001 - see __enter__
                pass
        return False


def maybe_jax_profiler_trace() -> _JaxTraceCtx:
    """The one knob for device-level profiling of the distributed
    executors: a context manager that activates ``jax.profiler.trace``
    into ``$TNC_TPU_TRACE_JAX`` when that env var names a directory and
    is a transparent no-op otherwise.

    >>> import os
    >>> os.environ.pop("TNC_TPU_TRACE_JAX", None) and None
    >>> with maybe_jax_profiler_trace():  # unset: pure no-op, no jax import
    ...     x = 1
    >>> x
    1
    """
    return _JaxTraceCtx()


refresh_from_env()
