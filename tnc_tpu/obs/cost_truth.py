"""Cost-truth loop: online calibration from production telemetry.

Every decision surface in the stack — planner objectives, kernel/chain
promotion, slicing budgets, replan margins, approx-tier quotes — prices
work through a :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`, but
that model is fit *offline* from bench runs, and the serving
:class:`~tnc_tpu.obs.slo.DriftDetector` can only *alert* when reality
diverges. This module closes the loop:

- :class:`ProductionSampler` reservoir-samples per-dispatch telemetry
  by (query type × power-of-two batch bucket) in the serving hot path.
  One ``offer()`` is a dict lookup, a counter bump and (past capacity)
  one seeded-RNG draw — suppressible like ``TNC_TPU_TRACE`` and
  overhead-pinned by ``scripts/cost_truth_smoke.py``.
- :func:`refit_model` streams the samples through the same
  ``time ≈ flops/F + bytes/B + c`` least-squares fit the offline
  calibration uses (:func:`~tnc_tpu.obs.calibrate.fit_device_model`),
  with **hysteresis**: a minimum sample count, a bounded per-term
  relative change per epoch (the clamp), and a minimum relative change
  below which the refit is a no-op — so one noisy epoch can never slew
  the fleet's pricing.
- :class:`ModelRegistry` persists each accepted fit as a **versioned**
  model generation with the plan-cache atomic-JSON discipline (unique
  temp file + ``os.replace``; corrupt entries deleted and counted,
  never raised). :class:`ModelRegistryWatcher` is the
  ``SharedCacheWatcher`` analogue: replicas sharing the registry
  directory poll a cheap byte fingerprint and stage new generations
  into their service, which adopts them **only at batch boundaries** —
  a trace never sees two models inside one dispatch.
- :class:`PlanScoreboard` accumulates measured dispatch seconds vs the
  seconds predicted at plan time, keyed by plan-cache key. The
  :class:`~tnc_tpu.serve.replan.BackgroundReplanner` margin compares
  candidates against the *measured* incumbent when the scoreboard is
  warm; a swapped plan whose measured cost regresses beyond tolerance
  within its first N batches (:class:`SwapWatch`) **auto-rolls back**
  to the prior plan, counted and regression-pinned so the bad plan is
  not re-adopted.

:class:`CostTruth` bundles the pieces into the controller a
:class:`~tnc_tpu.serve.service.ContractionService` owns
(``enable_cost_truth``); ``stats()["calibration"]`` and the
``/calibration`` telemetry endpoint surface its state.

>>> cfg = CostTruthConfig(refit_min_samples=2, refit_cooldown_s=0.0)
>>> ct = CostTruth(cfg, model=CalibratedCostModel(flops_per_s=1e9))
>>> ct.model_version
1
>>> for _ in range(4):
...     ct.observe_dispatch("amplitude", 1, 0.02, flops=1e7, nbytes=0.0,
...                         steps=1, plan_key="k", predicted_s=0.01)
>>> ct.maybe_refit(trigger="doctest")
True
>>> ct.adopt_pending() is not None
True
>>> ct.model_version
2
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import uuid
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Sequence

from tnc_tpu.obs.calibrate import (
    CalibratedCostModel,
    StepSample,
    fit_device_model,
)
from tnc_tpu.utils.digest import stable_digest

logger = logging.getLogger(__name__)

#: registry file name inside the registry directory — one generation
#: file per fleet (the version lives inside, monotone across publishes)
REGISTRY_FILE = "cost_model.json"

#: env kill switch, same discipline as TNC_TPU_TRACE: set to "0" to
#: suppress production sampling entirely (the hot-path hook reduces to
#: one attribute check)
ENV_SUPPRESS = "TNC_TPU_COST_TRUTH"


@dataclass(frozen=True)
class CostTruthConfig:
    """Knobs for the whole loop. The defaults are production-shaped:
    refits need evidence (``refit_min_samples``), move slowly
    (``max_rel_step`` per epoch), and never thrash
    (``refit_cooldown_s``, ``min_rel_change``)."""

    enabled: bool = True  # master switch for the production sampler
    reservoir_size: int = 64  # per-(type × bucket) retained samples
    refit_min_samples: int = 16  # distinct samples before a refit runs
    refit_cooldown_s: float = 5.0  # min seconds between refit epochs
    # hysteresis: each fitted constant moves at most this relative step
    # from the current model per epoch (0.5 = ±50%)
    max_rel_step: float = 0.5
    # a clamped fit within this relative distance of the current model
    # on every term is dropped (no version churn on noise)
    min_rel_change: float = 0.01
    # drain the reservoirs after an accepted refit so the next epoch
    # fits fresh traffic, not a stale mixture
    reset_after_refit: bool = True
    # merge the live registry's per-step spans (run_steps_timed /
    # TNC_TPU_STEP_TIME machinery) into the fit when present
    use_step_spans: bool = True
    # scoreboard: measured incumbent seconds need this many dispatches
    # before the replanner margin (or a rollback baseline) trusts them
    scoreboard_min_samples: int = 8
    scoreboard_max_plans: int = 64
    # rollback: watch the first N post-swap dispatches; if their mean
    # measured seconds exceed tolerance × the pre-swap baseline after
    # min_samples, restage the prior plan
    rollback_window: int = 8
    rollback_tolerance: float = 1.5
    rollback_min_samples: int = 3


@dataclass(frozen=True)
class DispatchSample:
    """One sampled dispatch: the per-dispatch totals the service can
    see (template-program flops/bytes, step count) next to the measured
    wall seconds."""

    kind: str
    bucket: int
    flops: float
    nbytes: float
    steps: int
    dur_s: float


class ProductionSampler:
    """Per-(type × bucket) reservoir sampling of dispatch telemetry.

    Classic Algorithm R per stratum with a seeded RNG (deterministic
    across runs for a given offer sequence): the first ``capacity``
    offers fill the reservoir, after which offer *i* replaces a random
    slot with probability ``capacity / i``. ``enabled=False`` turns
    :meth:`offer` into a single boolean check — the suppressed path the
    overhead pin measures.

    >>> s = ProductionSampler(capacity=2)
    >>> for i in range(10):
    ...     s.offer("amplitude", 1, 1e6, 0.0, 3, 0.001 * (i + 1))
    >>> s.counts()["offered"]
    10
    >>> s.counts()["kept"]
    2
    """

    def __init__(self, capacity: int = 64, enabled: bool = True):
        self.capacity = max(1, int(capacity))
        self.enabled = bool(enabled)
        self._rng = random.Random(0xC057)
        self._lock = threading.Lock()
        # stratum key (kind, bucket) -> [seen_count, list[DispatchSample]]
        self._strata: dict[tuple[str, int], list] = {}
        self._offered = 0

    def offer(
        self,
        kind: str,
        bucket: int,
        flops: float,
        nbytes: float,
        steps: int,
        dur_s: float,
    ) -> None:
        if not self.enabled:
            return
        sample = DispatchSample(
            kind, int(bucket), float(flops), float(nbytes),
            max(int(steps), 1), float(dur_s),
        )
        with self._lock:
            self._offered += 1
            stratum = self._strata.setdefault((kind, int(bucket)), [0, []])
            stratum[0] += 1
            kept = stratum[1]
            if len(kept) < self.capacity:
                kept.append(sample)
            else:
                j = self._rng.randrange(stratum[0])
                if j < self.capacity:
                    kept[j] = sample

    def samples(self) -> list[DispatchSample]:
        with self._lock:
            return [
                s for stratum in self._strata.values() for s in stratum[1]
            ]

    def fit_samples(self) -> list[StepSample]:
        """The reservoir contents as per-STEP samples for
        :func:`~tnc_tpu.obs.calibrate.fit_device_model`: each dispatch
        sample is normalized by its step count, so the fitted
        ``dispatch_s`` stays the per-step constant
        :meth:`CalibratedCostModel.op_seconds` expects."""
        out = []
        for s in self.samples():
            n = max(s.steps, 1)
            out.append(
                StepSample(
                    f"dispatch[{s.kind}/b{s.bucket}]",
                    s.flops / n, s.nbytes / n, s.dur_s / n,
                    source="serve",
                )
            )
        return out

    def counts(self) -> dict:
        with self._lock:
            kept = sum(len(st[1]) for st in self._strata.values())
            by_bucket = {
                f"{kind}/b{bucket}": {"seen": st[0], "kept": len(st[1])}
                for (kind, bucket), st in sorted(self._strata.items())
            }
            return {
                "offered": self._offered,
                "kept": kept,
                "buckets": by_bucket,
            }

    def reset(self) -> None:
        with self._lock:
            self._strata.clear()


def _clamp_term(
    current: float | None, fitted: float | None, max_rel_step: float
) -> tuple[float | None, bool]:
    """One fitted constant bounded to ``±max_rel_step`` relative change
    from the current value. A term the current model lacks adopts the
    fit directly (first epoch learns it); a term the FIT lacks keeps
    the current value (absence of evidence is not evidence the term
    vanished). Returns ``(value, clamped?)``."""
    if fitted is None:
        return current, False
    if current is None or current <= 0.0:
        return fitted, False
    lo = current / (1.0 + max_rel_step)
    hi = current * (1.0 + max_rel_step)
    if fitted < lo:
        return lo, True
    if fitted > hi:
        return hi, True
    return fitted, False


def refit_model(
    current: CalibratedCostModel | None,
    samples: Sequence[StepSample],
    config: CostTruthConfig,
) -> tuple[CalibratedCostModel | None, dict]:
    """One streaming-refit epoch: least-squares fit over ``samples``,
    per-term clamp against ``current``, significance gate. Returns
    ``(model, info)`` where ``model`` is None when no refit should be
    adopted (too few samples, degenerate fit, or change below
    ``min_rel_change``) and ``info`` records why.

    >>> cfg = CostTruthConfig(refit_min_samples=2)
    >>> cur = CalibratedCostModel(flops_per_s=2e9)
    >>> rows = [StepSample("a", 1e9, 0.0, 1.0), StepSample("b", 2e9, 0.0, 2.0)]
    >>> model, info = refit_model(cur, rows, cfg)
    >>> info["clamped"]  # raw fit is 1e9 flops/s: 2x off, clamped to 1.5x
    ['flops_per_s']
    >>> round(model.flops_per_s / 1e9, 3)
    1.333
    """
    info: dict = {"n_samples": len(samples)}
    if len(samples) < config.refit_min_samples:
        info["rejected"] = "min_samples"
        return None, info
    fitted = fit_device_model(samples)
    if fitted is None:
        info["rejected"] = "no_fit"
        return None, info
    info["fit"] = {
        "flops_per_s": fitted.flops_per_s,
        "bytes_per_s": fitted.bytes_per_s,
        "dispatch_s": fitted.dispatch_s,
        "terms": list(fitted.terms),
    }
    clamped: list[str] = []
    if current is None:
        new = CalibratedCostModel.from_device_model(fitted)
    else:
        f, c = _clamp_term(
            current.flops_per_s, fitted.flops_per_s, config.max_rel_step
        )
        if c:
            clamped.append("flops_per_s")
        d, c = _clamp_term(
            current.dispatch_s or None, fitted.dispatch_s or None,
            config.max_rel_step,
        )
        if c:
            clamped.append("dispatch_s")
        b, c = _clamp_term(
            current.bytes_per_s, fitted.bytes_per_s, config.max_rel_step
        )
        if c:
            clamped.append("bytes_per_s")
        new = CalibratedCostModel(f, d or 0.0, b)
        # significance gate: every term within min_rel_change of the
        # current model means nothing worth a new fleet-wide generation
        def _rel(a, b_):
            if not a and not b_:
                return 0.0
            if not a or not b_:
                return 1.0
            return abs(a - b_) / abs(a)

        moved = max(
            _rel(current.flops_per_s, new.flops_per_s),
            _rel(current.dispatch_s, new.dispatch_s),
            _rel(current.bytes_per_s, new.bytes_per_s),
        )
        info["moved"] = round(moved, 6)
        if moved < config.min_rel_change:
            info["rejected"] = "below_min_rel_change"
            return None, info
    info["clamped"] = clamped
    return new, info


class ModelRegistry:
    """Versioned on-disk cost-model generations.

    One ``cost_model.json`` per registry directory, written with the
    plan-cache atomic discipline: a uniquely named temp file is
    ``json.dump``-ed, flushed, fsynced and ``os.replace``-d over the
    entry, so N racing publishers leave whichever complete generation
    landed last and readers are lock-free. The document is
    :meth:`CalibratedCostModel.from_report`-compatible plus provenance
    (``version``, ``fitted_unix``, ``n_samples``, ``trigger``).

    >>> import tempfile
    >>> reg = ModelRegistry(tempfile.mkdtemp())
    >>> reg.publish(CalibratedCostModel(flops_per_s=1e9), trigger="seed")
    1
    >>> reg.publish(CalibratedCostModel(flops_per_s=2e9), trigger="drift")
    2
    >>> version, model = reg.latest()
    >>> version, round(model.flops_per_s / 1e9, 1)
    (2, 2.0)
    """

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.path = self.directory / REGISTRY_FILE
        self._counts = {
            "publish": 0, "load": 0, "corrupt": 0, "store_failed": 0,
        }
        self._lock = threading.Lock()

    def _count(self, key: str) -> None:
        with self._lock:
            self._counts[key] += 1

    def load(self) -> dict | None:
        """The raw current generation document (None when absent). A
        corrupt entry is deleted and counted, never raised — the
        plan-cache rule: bad bytes degrade to 'no model', not a crash."""
        try:
            raw = self.path.read_bytes()
        except OSError:
            return None
        self._count("load")
        try:
            doc = json.loads(raw.decode("utf-8"))
            if not isinstance(doc, dict) or "flops_per_s" not in doc:
                raise ValueError("not a model document")
            return doc
        except (ValueError, UnicodeDecodeError):
            self._count("corrupt")
            logger.warning(
                "cost-truth registry: corrupt model document %s deleted",
                self.path,
            )
            try:
                self.path.unlink()
            except OSError:
                pass
            return None

    def latest(self) -> tuple[int, CalibratedCostModel] | None:
        doc = self.load()
        if doc is None:
            return None
        try:
            return int(doc.get("version", 0)), CalibratedCostModel.from_report(
                doc
            )
        except (ValueError, TypeError, KeyError):
            self._count("corrupt")
            return None

    def publish(
        self,
        model: CalibratedCostModel,
        n_samples: int = 0,
        trigger: str = "",
        fitted_unix: float | None = None,
        extra: dict | None = None,
    ) -> int:
        """Write the next generation (current version + 1) atomically;
        returns the published version number."""
        doc = self.load()
        version = int(doc.get("version", 0)) + 1 if doc else 1
        out = {
            "version": version,
            "flops_per_s": model.flops_per_s,
            "dispatch_overhead_s": model.dispatch_s,
            "bytes_per_s": model.bytes_per_s,
            "fitted_unix": (
                time.time() if fitted_unix is None else float(fitted_unix)
            ),
            "n_samples": int(n_samples),
            "trigger": trigger,
        }
        if extra:
            out.update(extra)
        tmp = self.path.with_name(
            f"{REGISTRY_FILE}.{os.getpid()}.{uuid.uuid4().hex[:8]}.json.tmp"
        )
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(out, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError:
            self._count("store_failed")
            logger.warning(
                "cost-truth registry: publish failed", exc_info=True
            )
            try:
                tmp.unlink()
            except OSError:
                pass
            return version
        self._count("publish")
        return version

    def fingerprint(self) -> str | None:
        """Cheap byte digest of the current generation file — the
        watcher's change probe (same idiom as
        :meth:`~tnc_tpu.serve.plancache.PlanCache.entry_fingerprint`)."""
        try:
            raw = self.path.read_bytes()
        except OSError:
            return None
        return stable_digest("cost-model-bytes", raw)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._counts)


class ModelRegistryWatcher:
    """Adopt model generations published by OTHER replicas — the
    :class:`~tnc_tpu.serve.replan.SharedCacheWatcher` path for cost
    models. A fingerprint poll notices a new generation, loads it, and
    stages it on the service's :class:`CostTruth`; the dispatcher
    adopts it at the next batch boundary, so a fleet sharing one
    registry directory converges on one auditable model generation
    without any replica re-fitting.

    >>> ModelRegistryWatcher.__name__
    'ModelRegistryWatcher'
    """

    def __init__(self, service, registry: ModelRegistry,
                 poll_interval_s: float = 0.25):
        self.service = service
        self.registry = registry
        self.poll_interval_s = float(poll_interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._seen = registry.fingerprint()
        self.stats = {"adopts": 0, "skips": 0}

    def start(self) -> "ModelRegistryWatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tnc-serve-modelwatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=60.0)

    def __enter__(self) -> "ModelRegistryWatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def poll_once(self) -> bool:
        """One fingerprint probe; True when a foreign generation was
        staged for adoption."""
        fp = self.registry.fingerprint()
        if fp is None or fp == self._seen:
            return False
        self._seen = fp
        latest = self.registry.latest()
        if latest is None:
            return False
        version, model = latest
        ct = getattr(self.service, "_cost_truth", None)
        if ct is None or not ct.stage(version, model, origin="registry"):
            # our own publish (already current/staged), or an older
            # generation racing in: nothing to adopt
            self.stats["skips"] += 1
            return False
        self.stats["adopts"] += 1
        logger.info(
            "staged shared cost-model generation v%d for adoption", version
        )
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watcher must survive
                logger.exception("cost-model registry watch poll failed")


@dataclass
class _ScoreRow:
    n: int = 0
    total_s: float = 0.0
    ewma_s: float = 0.0
    predicted_s: float | None = None
    last_s: float = 0.0
    updated: float = 0.0


class PlanScoreboard:
    """Measured dispatch seconds vs plan-time predictions, per plan key.

    ``note(key, measured_s, predicted_s)`` folds one dispatch in;
    :meth:`measured_seconds` answers the replanner's margin question —
    "what does the incumbent plan actually cost?" — once the row has
    enough samples. Bounded: past ``max_plans`` keys the least recently
    updated row is evicted.

    >>> sb = PlanScoreboard(max_plans=4)
    >>> for _ in range(3):
    ...     sb.note("k", 0.02, predicted_s=0.01)
    >>> sb.measured_seconds("k", min_samples=3)
    0.02
    >>> sb.measured_seconds("k", min_samples=4) is None
    True
    """

    def __init__(self, max_plans: int = 64, alpha: float = 0.2):
        self.max_plans = max(1, int(max_plans))
        self.alpha = float(alpha)
        self._rows: dict[str, _ScoreRow] = {}
        self._lock = threading.Lock()

    def note(
        self, key: str, measured_s: float, predicted_s: float | None = None
    ) -> None:
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                if len(self._rows) >= self.max_plans:
                    oldest = min(
                        self._rows, key=lambda k: self._rows[k].updated
                    )
                    del self._rows[oldest]
                row = self._rows[key] = _ScoreRow()
            row.n += 1
            row.total_s += float(measured_s)
            row.ewma_s = (
                float(measured_s)
                if row.n == 1
                else self.alpha * float(measured_s)
                + (1.0 - self.alpha) * row.ewma_s
            )
            row.last_s = float(measured_s)
            if predicted_s is not None:
                row.predicted_s = float(predicted_s)
            row.updated = time.monotonic()

    def measured_seconds(
        self, key: str, min_samples: int = 1
    ) -> float | None:
        """Mean measured seconds per dispatch for ``key``, or None when
        the row is cold (fewer than ``min_samples`` dispatches)."""
        with self._lock:
            row = self._rows.get(key)
            if row is None or row.n < max(min_samples, 1):
                return None
            return row.total_s / row.n

    def rows(self) -> dict:
        with self._lock:
            out = {}
            for key, row in self._rows.items():
                mean = row.total_s / row.n if row.n else 0.0
                out[key] = {
                    "n": row.n,
                    "mean_s": round(mean, 6),
                    "ewma_s": round(row.ewma_s, 6),
                    "predicted_s": (
                        round(row.predicted_s, 6)
                        if row.predicted_s is not None
                        else None
                    ),
                    "measured_over_predicted": (
                        round(mean / row.predicted_s, 4)
                        if row.predicted_s
                        else None
                    ),
                }
            return out


@dataclass
class SwapWatch:
    """Post-swap regression watch: the first ``window`` measured
    dispatches of a newly adopted plan, judged against the pre-swap
    ``baseline_s``. Verdicts: ``"regressed"`` (mean measured exceeds
    ``tolerance × baseline`` after ``min_samples``), ``"ok"`` (window
    exhausted without regressing), None (still watching)."""

    key: str
    baseline_s: float
    window: int
    tolerance: float
    min_samples: int
    samples: list = field(default_factory=list)
    verdict: str | None = None

    def note(self, measured_s: float) -> str | None:
        if self.verdict is not None:
            return self.verdict
        self.samples.append(float(measured_s))
        n = len(self.samples)
        if n >= self.min_samples:
            mean = sum(self.samples) / n
            if mean > self.tolerance * self.baseline_s:
                self.verdict = "regressed"
                return self.verdict
        if n >= self.window:
            self.verdict = "ok"
        return self.verdict


class CostTruth:
    """The controller a serving process owns: sampler + refit + registry
    + scoreboard + rollback state, with the thread discipline the
    service needs (everything here is leaf-level: no method calls back
    into the service).

    Model adoption is two-phase by design: :meth:`stage` records a
    pending ``(version, model)`` and :meth:`adopt_pending` — called by
    the dispatcher at a batch boundary — makes it current, so no batch
    is ever priced (spanned, drift-predicted, quoted) under two model
    generations."""

    def __init__(
        self,
        config: CostTruthConfig | None = None,
        model: CalibratedCostModel | None = None,
        registry: ModelRegistry | None = None,
        clock=time.monotonic,
    ):
        self.config = config or CostTruthConfig()
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self.sampler = ProductionSampler(
            capacity=self.config.reservoir_size,
            enabled=self.config.enabled,
        )
        self.scoreboard = PlanScoreboard(
            max_plans=self.config.scoreboard_max_plans
        )
        self.counts = {
            "samples": 0, "refits": 0, "refit_rejected": 0,
            "publishes": 0, "model_adoptions": 0, "rollbacks": 0,
            "rollback_watches": 0, "rollback_pinned": 0,
        }
        self._pending: tuple[int, CalibratedCostModel, str] | None = None
        self._last_refit = -float("inf")
        self._last_refit_info: dict = {}
        self._fitted_unix: float | None = None
        self.swap_watch: SwapWatch | None = None
        self._rollback_bound = None  # the prior BoundProgram to restore
        self._rollback_staged = False
        self._pinned_sigs: set[str] = set()
        self.last_rollback: dict | None = None
        # seed generation: adopt the registry's current generation when
        # one exists (the fleet's source of truth beats a local offline
        # fit); otherwise publish the offline model as generation 1 so
        # the audit trail starts at the constants that were serving
        self.model = model
        self.model_version = 0
        if registry is not None:
            latest = registry.latest()
            if latest is not None:
                self.model_version, self.model = latest
            elif model is not None:
                self.model_version = registry.publish(
                    model, trigger="seed"
                )
                self.counts["publishes"] += 1
        elif model is not None:
            self.model_version = 1

    # -- hot path --------------------------------------------------------

    def observe_dispatch(
        self,
        kind: str,
        batch: int,
        dur_s: float,
        flops: float = 0.0,
        nbytes: float = 0.0,
        steps: int = 1,
        plan_key: str | None = None,
        predicted_s: float | None = None,
    ) -> str | None:
        """One measured dispatch: feed the sampler, the scoreboard and
        (when one is armed for ``plan_key``) the post-swap watch.
        Returns ``"rollback"`` exactly once, when the watch's verdict
        turns regressed — the caller (the service) then restages the
        prior plan."""
        if not self.config.enabled:
            return None
        with self._lock:
            self.counts["samples"] += 1
        if flops > 0.0:
            self.sampler.offer(kind, batch, flops, nbytes, steps, dur_s)
        if plan_key is None:
            return None
        self.scoreboard.note(plan_key, dur_s, predicted_s=predicted_s)
        with self._lock:
            watch = self.swap_watch
            if watch is None or watch.key != plan_key:
                return None
            verdict = watch.note(dur_s)
            if verdict is None:
                return None
            self.swap_watch = None
            if verdict != "regressed":
                self._rollback_bound = None
                return None
            # regression confirmed: pin the bad plan and hand the prior
            # bound back to the service for restaging
            self.counts["rollbacks"] += 1
            self.last_rollback = {
                "key": plan_key[:12],
                "baseline_s": round(watch.baseline_s, 6),
                "measured_s": round(
                    sum(watch.samples) / len(watch.samples), 6
                ),
                "tolerance": watch.tolerance,
                "samples": len(watch.samples),
            }
            return "rollback"

    # -- refit -----------------------------------------------------------

    def maybe_refit(
        self, trigger: str = "drift", now: float | None = None
    ) -> bool:
        """One refit epoch, gated by cooldown and sample count; on an
        accepted fit the new model is published to the registry (when
        one is attached) and staged for batch-boundary adoption.
        Returns True when a new generation was staged."""
        if not self.config.enabled:
            return False
        now = self._clock() if now is None else now
        with self._lock:
            if now - self._last_refit < self.config.refit_cooldown_s:
                return False
            self._last_refit = now
        rows = self.sampler.fit_samples()
        if self.config.use_step_spans:
            rows = rows + self._step_span_samples()
        new, info = refit_model(self.model, rows, self.config)
        info["trigger"] = trigger
        with self._lock:
            self._last_refit_info = info
        if new is None:
            with self._lock:
                self.counts["refit_rejected"] += 1
            return False
        fitted_unix = time.time()
        if self.registry is not None:
            version = self.registry.publish(
                new, n_samples=len(rows), trigger=trigger,
                fitted_unix=fitted_unix,
            )
            with self._lock:
                self.counts["publishes"] += 1
        else:
            version = self.model_version + 1
        staged = self.stage(version, new, origin="refit")
        if staged:
            with self._lock:
                self.counts["refits"] += 1
                self._fitted_unix = fitted_unix
            if self.config.reset_after_refit:
                self.sampler.reset()
            logger.info(
                "cost-truth refit (trigger=%s): staged model v%d "
                "(%.3e flops/s, %.1e s/dispatch)",
                trigger, version, new.flops_per_s, new.dispatch_s,
            )
        return staged

    def _step_span_samples(self) -> list[StepSample]:
        """Live per-step span samples (the ``run_steps_timed`` /
        ``TNC_TPU_STEP_TIME`` machinery), when obs tracing is on —
        merged into the refit so device-step truth sharpens the
        dispatch-level fit. Best-effort: tracing off → empty."""
        try:
            from tnc_tpu import obs
            from tnc_tpu.obs.calibrate import (
                aggregate_samples,
                pick_source,
                step_samples,
            )

            if not obs.enabled():
                return []
            rows = aggregate_samples(step_samples())
            source = pick_source(rows)
            return [s for s in rows if s.source == source]
        except Exception:  # noqa: BLE001 — sampling must never raise
            return []

    # -- model adoption --------------------------------------------------

    def stage(
        self, version: int, model: CalibratedCostModel, origin: str = ""
    ) -> bool:
        """Record a pending generation for batch-boundary adoption.
        False (no-op) when ``version`` is not newer than the current or
        already-staged generation — the guard that keeps a replica's
        own publish from round-tripping through the watcher."""
        with self._lock:
            if version <= self.model_version:
                return False
            if self._pending is not None and version <= self._pending[0]:
                return False
            self._pending = (int(version), model, origin)
            return True

    def adopt_pending(self) -> tuple[int, CalibratedCostModel] | None:
        """Make the staged generation current (the dispatcher calls
        this at batch boundaries, next to plan-swap adoption). Returns
        ``(version, model)`` when an adoption happened."""
        with self._lock:
            pending, self._pending = self._pending, None
            if pending is None:
                return None
            version, model, _origin = pending
            self.model = model
            self.model_version = version
            self.counts["model_adoptions"] += 1
        return version, model

    # -- rollback plumbing -----------------------------------------------

    def arm_swap_watch(self, key: str, prior_bound, bad_sig: str | None,
                       baseline_s: float | None) -> bool:
        """Arm the post-swap regression watch after a plan adoption.
        Needs a measured (or predicted) baseline; without one the swap
        is unwatchable and simply trusted. ``prior_bound`` is what a
        rollback restores; ``bad_sig`` is the adopted plan's signature,
        pinned on rollback so the regressed plan cannot be re-adopted."""
        if self.config.rollback_window <= 0 or baseline_s is None:
            return False
        if baseline_s <= 0.0 or prior_bound is None:
            return False
        with self._lock:
            if self._rollback_staged:
                # the adoption IS the rollback: restore trust, no watch
                self._rollback_staged = False
                return False
            self.swap_watch = SwapWatch(
                key=key,
                baseline_s=float(baseline_s),
                window=self.config.rollback_window,
                tolerance=self.config.rollback_tolerance,
                min_samples=self.config.rollback_min_samples,
            )
            self._rollback_bound = prior_bound
            self._bad_sig = bad_sig
            self.counts["rollback_watches"] += 1
        return True

    def take_rollback(self):
        """Consume the rollback: pin the regressed plan's signature and
        return the prior bound to restage (None when already taken)."""
        with self._lock:
            bound, self._rollback_bound = self._rollback_bound, None
            if bound is None:
                return None
            bad_sig = getattr(self, "_bad_sig", None)
            if bad_sig is not None and bad_sig not in self._pinned_sigs:
                self._pinned_sigs.add(bad_sig)
                self.counts["rollback_pinned"] += 1
            self._rollback_staged = True
            return bound

    def is_pinned(self, sig: str | None) -> bool:
        if sig is None:
            return False
        with self._lock:
            return sig in self._pinned_sigs

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + n

    # -- surfaces --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
            pending = self._pending
            watch = self.swap_watch
            last_refit_info = dict(self._last_refit_info)
            fitted_unix = self._fitted_unix
            last_rollback = (
                dict(self.last_rollback) if self.last_rollback else None
            )
            pinned = len(self._pinned_sigs)
        model = self.model
        out = {
            "enabled": self.config.enabled,
            "model_version": self.model_version,
            "model": (
                {
                    "flops_per_s": model.flops_per_s,
                    "dispatch_s": model.dispatch_s,
                    "bytes_per_s": model.bytes_per_s,
                }
                if model is not None
                else None
            ),
            "fitted_unix": fitted_unix,
            "pending_version": pending[0] if pending else None,
            "counts": counts,
            "sampler": self.sampler.counts(),
            "last_refit": last_refit_info,
            "scoreboard": self.scoreboard.rows(),
            "swap_watch": (
                {
                    "key": watch.key[:12],
                    "baseline_s": round(watch.baseline_s, 6),
                    "samples": len(watch.samples),
                    "window": watch.window,
                }
                if watch is not None
                else None
            ),
            "last_rollback": last_rollback,
            "pinned_plans": pinned,
        }
        if self.registry is not None:
            out["registry"] = self.registry.stats()
        return out


def config_from_env(
    config: CostTruthConfig | None = None,
) -> CostTruthConfig:
    """Apply the ``TNC_TPU_COST_TRUTH`` kill switch to a config — the
    same one-env-var suppression discipline as ``TNC_TPU_TRACE``."""
    cfg = config or CostTruthConfig()
    if os.environ.get(ENV_SUPPRESS, "1") == "0":
        cfg = replace(cfg, enabled=False)
    return cfg
