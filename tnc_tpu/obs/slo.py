"""Serving SLO engine: burn-rate alerts + calibrated drift detection.

ROADMAP item 5 names the gap this module closes: the service records
per-request latencies and the calibrated cost model predicts seconds
for every dispatch, but nothing *compares* them to an objective — nobody
can answer "is the fleet meeting its latency SLO right now, and is the
hardware drifting from the calibrated model?". Three pieces:

- **Objectives** (:class:`LatencyObjective`): declarative per-query-type
  targets — "99% of ``amplitude`` requests complete within 50 ms". A
  request is *bad* when it misses the latency threshold or terminates
  in any non-``completed`` outcome (failed / expired / rejected /
  cancelled — the server burned budget either way).
- **Multi-window burn rates**: the SRE-book alerting rule. For an
  objective with target ``f`` the error budget is ``1 - f``; the burn
  rate over a window is ``bad_fraction / budget`` (burn 1.0 = spending
  exactly the budget). An alert needs the burn to exceed the window
  pair's ``factor`` over BOTH the short and the long window — the short
  window makes alerts fast, the long window keeps a transient blip from
  paging (:class:`BurnWindow`).
- **Drift detection** (:class:`DriftDetector`): per executor bucket
  (query type × batch-size bucket), an EWMA of the ratio of measured
  dispatch seconds to the :class:`~tnc_tpu.obs.calibrate.
  CalibratedCostModel` prediction. A healthy fleet holds the ratio
  near its baseline; hardware degradation, a bad plan swap, or a
  co-tenant stealing the machine moves it — the ROADMAP's
  predicted-vs-measured incident signal, computed from data each
  dispatch already carries. ``baseline_samples > 0`` self-baselines
  each bucket on its first observations, so drift means "changed since
  this service started", robust to a miscalibrated model.

Alerts are **edge-triggered** for side effects (one ``slo.alerts``
counter bump + one warning log when an alert starts firing) and
**level-read** for state: :meth:`SLOEngine.check` returns what is
firing *now*, and the service surfaces it as ``stats()["slo"]["alerts"]``
and the ``/slo`` endpoint (:mod:`tnc_tpu.obs.http`).

Everything takes an injectable clock so the burn math is testable with
synthetic timelines (``tests/test_slo.py``).

>>> cfg = SLOConfig(
...     objectives=(LatencyObjective("amplitude", 0.05, target=0.9),),
...     windows=(BurnWindow(10.0, 40.0, 2.0),), min_requests=4)
>>> eng = SLOEngine(cfg, clock=lambda: 100.0)
>>> for _ in range(8):
...     eng.record_request("amplitude", 0.5, "completed", t=99.0)
>>> [a["kind"] for a in eng.check(t=100.0)]
['burn']
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from tnc_tpu.obs import core as obs_core

logger = logging.getLogger(__name__)

#: terminal request outcomes the engine accounts (everything but
#: ``completed`` consumes error budget)
OUTCOMES = ("completed", "failed", "expired", "rejected", "cancelled")


@dataclass(frozen=True)
class LatencyObjective:
    """One declarative objective: ``target`` fraction of ``type``
    requests must complete within ``threshold_s``. ``type="*"`` matches
    every query type (one fleet-wide objective)."""

    type: str
    threshold_s: float
    target: float = 0.99

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if self.threshold_s <= 0.0:
            raise ValueError("threshold_s must be positive")

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def matches(self, kind: str) -> bool:
        return self.type == "*" or self.type == kind

    def is_bad(self, latency_s: float, outcome: str) -> bool:
        return outcome != "completed" or latency_s > self.threshold_s


@dataclass(frozen=True)
class BurnWindow:
    """A short/long window pair with the burn-rate ``factor`` both must
    exceed to alert (multi-window, multi-burn-rate alerting)."""

    short_s: float
    long_s: float
    factor: float

    def __post_init__(self):
        if not 0.0 < self.short_s <= self.long_s:
            raise ValueError("need 0 < short_s <= long_s")
        if self.factor <= 0.0:
            raise ValueError("factor must be positive")


#: classic page/ticket pair: 14.4x over 5m+1h pages, 6x over 30m+6h
#: tickets (both scaled to the budget)
DEFAULT_WINDOWS = (
    BurnWindow(300.0, 3600.0, 14.4),
    BurnWindow(1800.0, 21600.0, 6.0),
)


@dataclass(frozen=True)
class SLOConfig:
    """Engine configuration. ``drift_baseline_samples > 0`` (the
    default) makes drift self-relative: each bucket's first N
    observations set its baseline ratio, which absorbs per-bucket
    systematics the per-dispatch prediction cannot see (batched
    dispatch work scales with batch size; the cost model predicts one
    dispatch). Set 0 only when the prediction is absolute-trustworthy
    for every bucket — the raw ratio is then compared to 1 directly."""

    objectives: tuple = ()
    windows: tuple = DEFAULT_WINDOWS
    min_requests: int = 10  # short-window events below this never alert
    drift_threshold: float = 1.5  # alert when ratio leaves [1/t, t]
    drift_alpha: float = 0.2  # EWMA weight of the newest sample
    drift_min_samples: int = 8  # per bucket, before drift may alert
    drift_baseline_samples: int = 8
    max_timelines: int = 256  # recent per-request timelines retained
    # hard cap on retained request events: the burn windows bound
    # retention in TIME, this bounds it in COUNT (a 100-rps service
    # with the default 6h long window would otherwise hold millions of
    # tuples and pay a full scan per evaluation — the scan runs on the
    # dispatcher thread each check interval). Past the cap the oldest
    # events drop and long-window burn under-counts — bounded like the
    # obs span cap, loud in the config rather than silent OOM.
    max_events: int = 20_000


@dataclass
class _Bucket:
    """Per-executor-bucket drift state."""

    ewma: float = 0.0
    n: int = 0
    baseline: float = 1.0
    baseline_done: bool = False
    calibrated: bool = False  # bucket mode, fixed by its FIRST sample
    _warmup: list = field(default_factory=list)


class DriftDetector:
    """EWMA of measured-vs-predicted dispatch seconds per bucket.

    ``update(bucket, predicted_s, measured_s)`` folds one dispatch in;
    with ``predicted_s`` None/0 the raw measured seconds are tracked
    instead (self-baselining then makes the ratio unitless). The
    detector alerts when a bucket's normalized ratio leaves
    ``[1/threshold, threshold]`` after ``min_samples`` — both slowdowns
    and "suspiciously fast" (a plan swap that stopped doing the work)
    are incidents.

    >>> d = DriftDetector(threshold=1.5, alpha=0.5, min_samples=2)
    >>> d.update("amp/b8", 0.010, 0.010)
    >>> d.update("amp/b8", 0.010, 0.010)
    >>> d.alerting()
    {}
    >>> for _ in range(8):
    ...     d.update("amp/b8", 0.010, 0.100)
    >>> round(d.alerting()["amp/b8"], 1) > 1.5
    True
    """

    def __init__(
        self,
        threshold: float = 1.5,
        alpha: float = 0.2,
        min_samples: int = 8,
        baseline_samples: int = 0,
    ):
        if threshold <= 1.0:
            raise ValueError("drift threshold must be > 1")
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.threshold = float(threshold)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.baseline_samples = int(baseline_samples)
        self._buckets: dict[str, _Bucket] = {}

    def update(
        self, bucket: str, predicted_s: float | None, measured_s: float
    ) -> None:
        calibrated = bool(predicted_s and predicted_s > 0.0)
        b = self._buckets.setdefault(bucket, _Bucket())
        if b.n == 0:
            b.calibrated = calibrated
        elif b.calibrated != calibrated:
            # ratio and raw-seconds samples must never share an EWMA —
            # that would fabricate drift. A calibrated bucket drops a
            # raw sample (prediction hiccup, e.g. during a plan swap);
            # a RAW bucket whose predictions come online restarts in
            # calibrated mode — freezing it would silently disable
            # drift for that bucket forever over one first-dispatch
            # hiccup.
            if calibrated:
                b = self._buckets[bucket] = _Bucket(calibrated=True)
            else:
                obs_core.counter_add("slo.drift.dropped", bucket=bucket)
                return
        ratio = (
            measured_s / predicted_s if calibrated else float(measured_s)
        )
        b.n += 1
        b.ewma = (
            ratio
            if b.n == 1
            else self.alpha * ratio + (1.0 - self.alpha) * b.ewma
        )
        if self.baseline_samples > 0 and not b.baseline_done:
            b._warmup.append(ratio)
            if len(b._warmup) >= self.baseline_samples:
                mid = sorted(b._warmup)
                b.baseline = mid[len(mid) // 2] or 1.0
                b.baseline_done = True
                b._warmup.clear()

    def _normalized(self, b: _Bucket) -> float:
        return b.ewma / b.baseline if b.baseline else b.ewma

    def _bucket_alerting(self, b: _Bucket) -> bool:
        if b.n < self.min_samples:
            return False
        if self.baseline_samples > 0:
            if not b.baseline_done:
                return False
        elif not b.calibrated:
            # raw measured seconds with no baseline to normalize them:
            # the ratio band is unitless and the comparison meaningless
            return False
        r = self._normalized(b)
        return r > self.threshold or (r > 0.0 and r < 1.0 / self.threshold)

    def alerting(self) -> dict[str, float]:
        """``{bucket: normalized ratio}`` for every drifting bucket."""
        return {
            name: self._normalized(b)
            for name, b in self._buckets.items()
            if self._bucket_alerting(b)
        }

    def stats(self) -> dict[str, dict]:
        """Per-bucket state rows. ``n``/``ewma``/``baseline`` expose the
        sample counts and EWMA state ``min_samples``/``baseline_samples``
        tuning needs to be observable; ``calibrated`` says whether the
        bucket tracks measured/predicted ratios or raw seconds."""
        return {
            name: {
                "ratio": round(self._normalized(b), 4),
                "ewma": round(b.ewma, 6),
                "baseline": round(b.baseline, 6),
                "n": b.n,
                "calibrated": b.calibrated,
                "baseline_done": b.baseline_done,
                "alerting": self._bucket_alerting(b),
            }
            for name, b in self._buckets.items()
        }


class SLOEngine:
    """Burn-rate + drift evaluation over a live request stream.

    The serving layer calls :meth:`record_request` at every terminal
    outcome and :meth:`record_dispatch` after every batch dispatch;
    :meth:`check` (cheap, called at batch boundaries and by ``stats()``)
    evaluates every objective window pair and drift bucket, fires
    edge-triggered side effects for NEW alerts (``slo.alerts`` counter,
    warning log), and returns the currently-firing alert list. All
    public methods are thread-safe.
    """

    def __init__(self, config: SLOConfig | None = None, clock=time.monotonic):
        self.config = config or SLOConfig()
        self._clock = clock
        self._lock = threading.Lock()
        # (t, kind, latency_s, bad-per-objective tuple)
        self._events: deque = deque(maxlen=self.config.max_events)
        self._outcome_counts: dict[str, int] = {o: 0 for o in OUTCOMES}
        self._timelines: deque = deque(maxlen=self.config.max_timelines)
        self.drift = DriftDetector(
            threshold=self.config.drift_threshold,
            alpha=self.config.drift_alpha,
            min_samples=self.config.drift_min_samples,
            baseline_samples=self.config.drift_baseline_samples,
        )
        self._active: dict[str, dict] = {}
        self._alerts_total = 0
        # dispatches the drift detector never saw, per bucket: kinds
        # whose handlers declare drift_stable=False are excluded from
        # drift (their per-bucket seconds are not comparable), but the
        # excluded volume must stay visible or min_requests tuning
        # reads "no drift" as "no traffic"
        self._drift_excluded: dict[str, int] = {}
        self._horizon = max(
            (w.long_s for w in self.config.windows), default=0.0
        )

    def _now(self, t: float | None) -> float:
        return self._clock() if t is None else float(t)

    # -- ingestion -------------------------------------------------------

    def record_request(
        self,
        kind: str,
        latency_s: float,
        outcome: str = "completed",
        t: float | None = None,
        timeline: dict | None = None,
    ) -> None:
        """One terminal request outcome. ``timeline`` (optional) is the
        request's plain-data trace record, retained in a bounded ring
        for the ``/slo`` endpoint's recent-requests view."""
        t = self._now(t)
        bad = tuple(
            obj.matches(kind) and obj.is_bad(latency_s, outcome)
            for obj in self.config.objectives
        )
        with self._lock:
            self._events.append((t, kind, float(latency_s), bad))
            self._outcome_counts[outcome] = (
                self._outcome_counts.get(outcome, 0) + 1
            )
            if timeline is not None:
                self._timelines.append(timeline)
            self._prune(t)

    def record_dispatch(
        self, bucket: str, predicted_s: float | None, measured_s: float
    ) -> None:
        """One batch dispatch's measured wall seconds next to the
        calibrated prediction (None when no cost model is attached —
        drift then tracks raw measured seconds per bucket)."""
        with self._lock:
            self.drift.update(bucket, predicted_s, measured_s)

    def record_dispatch_excluded(self, bucket: str) -> None:
        """One dispatch of a payload-variant (``drift_stable=False``)
        kind, deliberately NOT fed to the drift detector — counted per
        bucket so the exclusion is observable instead of silent."""
        with self._lock:
            self._drift_excluded[bucket] = (
                self._drift_excluded.get(bucket, 0) + 1
            )

    def _prune(self, now: float) -> None:
        horizon = self._horizon
        while self._events and now - self._events[0][0] > horizon:
            self._events.popleft()

    # -- evaluation ------------------------------------------------------

    def burn_rates(self, t: float | None = None) -> list[dict]:
        """Current burn per objective per window pair (the ``/slo`` and
        ``stats()`` surface). ONE pass over the event deque accumulates
        (total, bad) per objective per distinct window edge — this runs
        on the serving dispatcher thread every check interval, so the
        scan cost must not multiply by objectives x windows."""
        now = self._now(t)
        objs = self.config.objectives
        edges = sorted(
            {e for w in self.config.windows for e in (w.short_s, w.long_s)}
        )
        # counts[obj_idx][edge] = [total, bad]
        counts = [{e: [0, 0] for e in edges} for _ in objs]
        with self._lock:
            for tev, kind, _lat, flags in self._events:
                age = now - tev
                if edges and age > edges[-1]:
                    continue
                for i, obj in enumerate(objs):
                    if not obj.matches(kind):
                        continue
                    bad = 1 if flags[i] else 0
                    for e in edges:
                        if age <= e:
                            c = counts[i][e]
                            c[0] += 1
                            c[1] += bad
        out = []
        for i, obj in enumerate(objs):
            row = {
                "type": obj.type,
                "threshold_s": obj.threshold_s,
                "target": obj.target,
                "windows": [],
            }
            for w in self.config.windows:
                ts, bads = counts[i][w.short_s]
                tl, badl = counts[i][w.long_s]
                bs = (bads / ts) / obj.budget if ts else 0.0
                bl = (badl / tl) / obj.budget if tl else 0.0
                row["windows"].append(
                    {
                        "short_s": w.short_s,
                        "long_s": w.long_s,
                        "factor": w.factor,
                        "burn_short": round(bs, 4),
                        "burn_long": round(bl, 4),
                        "events_short": ts,
                        "alerting": (
                            ts >= self.config.min_requests
                            and bs > w.factor
                            and bl > w.factor
                        ),
                    }
                )
            out.append(row)
        return out

    def check(self, t: float | None = None) -> list[dict]:
        """Evaluate everything; fire side effects for alerts that are
        NEW since the last check; return the currently-firing alerts."""
        now = self._now(t)
        return self._evaluate(self.burn_rates(now), now)

    def _evaluate(self, burn_rows: list[dict], now: float) -> list[dict]:
        """Alert evaluation over precomputed burn rows (so ``stats()``
        scans the event window once, not twice)."""
        active: dict[str, dict] = {}
        for row in burn_rows:
            for w in row["windows"]:
                if not w["alerting"]:
                    continue
                key = f"burn:{row['type']}:{w['short_s']:g}s"
                active[key] = {
                    "kind": "burn",
                    "key": key,
                    "type": row["type"],
                    "value": min(w["burn_short"], w["burn_long"]),
                    "threshold": w["factor"],
                    "detail": (
                        f"burn {w['burn_short']:.1f}x/{w['burn_long']:.1f}x "
                        f"over {w['short_s']:g}s/{w['long_s']:g}s windows "
                        f"(budget factor {w['factor']:g})"
                    ),
                }
        with self._lock:
            for bucket, ratio in self.drift.alerting().items():
                key = f"drift:{bucket}"
                active[key] = {
                    "kind": "drift",
                    "key": key,
                    "bucket": bucket,
                    "value": round(ratio, 4),
                    "threshold": self.config.drift_threshold,
                    "detail": (
                        f"measured/predicted dispatch ratio {ratio:.2f} "
                        f"left [{1 / self.config.drift_threshold:.2f}, "
                        f"{self.config.drift_threshold:.2f}]"
                    ),
                }
            fresh = [a for k, a in active.items() if k not in self._active]
            self._active = active
            self._alerts_total += len(fresh)
        for alert in fresh:
            obs_core.counter_add("slo.alerts", kind=alert["kind"])
            logger.warning("SLO alert: %s — %s", alert["key"], alert["detail"])
        return list(active.values())

    # -- surfaces --------------------------------------------------------

    def timelines(self) -> list[dict]:
        """Most recent per-request timeline records (bounded ring)."""
        with self._lock:
            return list(self._timelines)

    def stats(self, t: float | None = None) -> dict:
        """Plain-data snapshot: objectives + burns, drift buckets, the
        firing alerts, and outcome totals — the ``stats()["slo"]`` block
        and the ``/slo`` endpoint body."""
        now = self._now(t)
        burn_rows = self.burn_rates(now)
        alerts = self._evaluate(burn_rows, now)
        with self._lock:
            outcomes = dict(self._outcome_counts)
            drift = self.drift.stats()
            excluded = dict(self._drift_excluded)
            total = self._alerts_total
        return {
            "objectives": burn_rows,
            "drift": drift,
            "drift_excluded": excluded,
            "alerts": alerts,
            "alerts_total": total,
            "outcomes": outcomes,
        }
