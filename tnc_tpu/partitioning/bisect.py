"""Multilevel hypergraph bisection — the native KaHyPar replacement.

The reference links the KaHyPar C++ library for min-cut hypergraph
partitioning (``tnc/src/tensornetwork/partitioning.rs:6,76-89``). This is
an original multilevel implementation of the same algorithm family:

1. **Coarsening** — heavy-edge matching: repeatedly merge the pair of
   vertices sharing the heaviest connection until the graph is small.
2. **Initial partitioning** — BFS region growing from random seeds,
   several attempts, keep the best cut.
3. **Uncoarsening + FM refinement** — project the partition back up,
   running Fiduccia–Mattheyses passes (gain-ordered boundary moves with a
   balance constraint, best-prefix rollback) at every level.

k-way partitioning is recursive bisection with proportional target
weights, as KaHyPar's recursive-bisection mode does.
"""

from __future__ import annotations

import heapq
import random
from dataclasses import dataclass

from tnc_tpu.partitioning.hypergraph import Hypergraph


@dataclass
class _CoarseLevel:
    graph: Hypergraph
    # map from coarse vertex -> list of fine vertices
    members: list[list[int]]


def _coarsen_once(hg: Hypergraph, rng: random.Random) -> _CoarseLevel | None:
    """One round of heavy-edge matching. Returns None when no progress."""
    n = hg.num_vertices
    # connection weight between vertex pairs via shared (small) hyperedges
    order = list(range(n))
    rng.shuffle(order)
    matched = [-1] * n
    for v in order:
        if matched[v] >= 0:
            continue
        best_u = -1
        best_w = 0.0
        conn: dict[int, float] = {}
        for e in hg.vertex_edges[v]:
            pins = hg.edge_pins[e]
            if len(pins) > 8:  # skip huge hyperedges during matching
                continue
            w = hg.edge_weights[e] / (len(pins) - 1)
            for u in pins:
                if u != v and matched[u] < 0:
                    conn[u] = conn.get(u, 0.0) + w
        for u, w in conn.items():
            if w > best_w:
                best_w, best_u = w, u
        if best_u >= 0:
            matched[v] = best_u
            matched[best_u] = v

    # build coarse graph
    coarse_id = [-1] * n
    members: list[list[int]] = []
    for v in range(n):
        if coarse_id[v] >= 0:
            continue
        u = matched[v]
        cid = len(members)
        if u >= 0 and u != v:
            members.append([v, u])
            coarse_id[v] = coarse_id[u] = cid
        else:
            members.append([v])
            coarse_id[v] = cid

    if len(members) >= n:  # no progress
        return None

    vertex_weights = [
        sum(hg.vertex_weights[v] for v in group) for group in members
    ]
    edge_map: dict[tuple[int, ...], float] = {}
    for pins, w in zip(hg.edge_pins, hg.edge_weights):
        coarse_pins = tuple(sorted({coarse_id[v] for v in pins}))
        if len(coarse_pins) < 2:
            continue
        edge_map[coarse_pins] = edge_map.get(coarse_pins, 0.0) + w
    edge_pins = [list(p) for p in edge_map]
    edge_weights = list(edge_map.values())
    coarse = Hypergraph(len(members), vertex_weights, edge_pins, edge_weights)
    return _CoarseLevel(coarse, members)


def _initial_partition(
    hg: Hypergraph, target0: float, imbalance: float, rng: random.Random, attempts: int = 8
) -> list[int]:
    """BFS region growing: grow block 0 from a random seed to its target
    weight; best cut over several attempts wins."""
    best: list[int] | None = None
    best_cut = float("inf")
    max0 = target0 * (1.0 + imbalance)
    for _ in range(max(1, attempts)):
        part = [1] * hg.num_vertices
        seed = rng.randrange(hg.num_vertices)
        weight0 = 0.0
        frontier = [seed]
        seen = {seed}
        while frontier and weight0 < target0:
            v = frontier.pop()
            if weight0 + hg.vertex_weights[v] > max0:
                continue
            part[v] = 0
            weight0 += hg.vertex_weights[v]
            for e in hg.vertex_edges[v]:
                for u in hg.edge_pins[e]:
                    if u not in seen:
                        seen.add(u)
                        frontier.insert(0, u)
        cut = hg.cut_weight(part)
        if cut < best_cut:
            best_cut = cut
            best = part
    assert best is not None
    return best


def _fm_refine(
    hg: Hypergraph,
    part: list[int],
    target0: float,
    imbalance: float,
    max_passes: int = 8,
) -> None:
    """Fiduccia–Mattheyses boundary refinement, in place."""
    n = hg.num_vertices
    total = hg.total_vertex_weight()
    min0 = target0 * (1.0 - imbalance)
    max0 = target0 * (1.0 + imbalance)

    # per-edge pin counts in each block
    for _pass in range(max_passes):
        pins_in: list[list[int]] = [[0, 0] for _ in hg.edge_pins]
        for e, pins in enumerate(hg.edge_pins):
            for v in pins:
                pins_in[e][part[v]] += 1
        weight0 = sum(w for v, w in enumerate(hg.vertex_weights) if part[v] == 0)

        def gain(v: int) -> float:
            g = 0.0
            side = part[v]
            other = 1 - side
            for e in hg.vertex_edges[v]:
                if pins_in[e][side] == 1:
                    g += hg.edge_weights[e]  # edge becomes uncut
                if pins_in[e][other] == 0:
                    g -= hg.edge_weights[e]  # edge becomes cut
            return g

        heap: list[tuple[float, int]] = []
        for v in range(n):
            heapq.heappush(heap, (-gain(v), v))

        locked = [False] * n
        moves: list[int] = []
        cum_gain = 0.0
        best_gain = 0.0
        best_prefix = 0

        while heap:
            neg_g, v = heapq.heappop(heap)
            if locked[v]:
                continue
            g = gain(v)
            if -neg_g != g:  # stale entry: reinsert with fresh gain
                heapq.heappush(heap, (-g, v))
                continue
            # balance check for the move
            w = hg.vertex_weights[v]
            new_weight0 = weight0 - w if part[v] == 0 else weight0 + w
            if not (min0 <= new_weight0 <= max0) and total > w:
                locked[v] = True  # cannot move this pass
                continue
            # apply move
            side = part[v]
            for e in hg.vertex_edges[v]:
                pins_in[e][side] -= 1
                pins_in[e][1 - side] += 1
            part[v] = 1 - side
            weight0 = new_weight0
            locked[v] = True
            cum_gain += g
            moves.append(v)
            if cum_gain > best_gain + 1e-12:
                best_gain = cum_gain
                best_prefix = len(moves)
            # refresh neighbors
            for e in hg.vertex_edges[v]:
                for u in hg.edge_pins[e]:
                    if not locked[u]:
                        heapq.heappush(heap, (-gain(u), u))

        # roll back past the best prefix
        for v in moves[best_prefix:]:
            part[v] = 1 - part[v]
        if best_gain <= 1e-12:
            break


def bisect(
    hg: Hypergraph,
    imbalance: float = 0.03,
    rng: random.Random | None = None,
    target_fraction: float = 0.5,
    coarsen_to: int = 80,
) -> list[int]:
    """Multilevel 2-way partition of ``hg``; returns block ids (0/1).

    >>> import random
    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> from tnc_tpu.partitioning.hypergraph import hypergraph_from_tensors
    >>> ring = [LeafTensor([i, (i + 1) % 6], [2, 2]) for i in range(6)]
    >>> blocks = bisect(hypergraph_from_tensors(ring), rng=random.Random(0))
    >>> sorted(set(blocks)), len(blocks)
    ([0, 1], 6)
    """
    if rng is None:
        rng = random.Random(42)
    if hg.num_vertices <= 1:
        return [0] * hg.num_vertices

    target0 = hg.total_vertex_weight() * target_fraction

    # Coarsening phase
    levels: list[_CoarseLevel] = []
    current = hg
    while current.num_vertices > coarsen_to:
        level = _coarsen_once(current, rng)
        if level is None:
            break
        levels.append(level)
        current = level.graph

    # Initial partition at the coarsest level
    part = _initial_partition(current, target0, imbalance, rng)
    _fm_refine(current, part, target0, imbalance)

    # Uncoarsen + refine
    for i in range(len(levels) - 1, -1, -1):
        level = levels[i]
        fine_graph = hg if i == 0 else levels[i - 1].graph
        fine_part = [0] * fine_graph.num_vertices
        for cid, group in enumerate(level.members):
            for v in group:
                fine_part[v] = part[cid]
        part = fine_part
        _fm_refine(fine_graph, part, target0, imbalance)

    return part


def kway_refine_km1(
    hg: Hypergraph,
    part: list[int],
    k: int,
    imbalance: float = 0.03,
    max_passes: int = 8,
) -> None:
    """Direct k-way move-based refinement under the connectivity (km1)
    objective ``sum_e w_e * (lambda_e - 1)``, in place.

    This is where the km1 preset genuinely diverges from cut-based
    recursive bisection: in any 2-way split ``lambda - 1`` equals the
    cut indicator, so only a k-way pass can tell the objectives apart —
    the same reason KaHyPar ships cut and km1 as distinct configs
    (``tnc/src/tensornetwork/partition_config.rs:12-36``). Python
    oracle of the native ``kway_refine_km1`` (``native/partitioner.cpp``).
    """
    n = hg.num_vertices
    if k <= 1 or n <= 1:
        return
    maxb = hg.total_vertex_weight() / k * (1.0 + imbalance)
    pins_in = [[0] * k for _ in hg.edge_pins]
    for e, pins in enumerate(hg.edge_pins):
        for v in pins:
            pins_in[e][part[v]] += 1
    block_w = [0.0] * k
    for v in range(n):
        block_w[part[v]] += hg.vertex_weights[v]

    for _pass in range(max_passes):
        moved = False
        for v in range(n):
            a = part[v]
            remove_gain = sum(
                hg.edge_weights[e]
                for e in hg.vertex_edges[v]
                if pins_in[e][a] == 1
            )
            best_b = -1
            best_gain = 1e-12
            tried = {a}
            for e in hg.vertex_edges[v]:
                for u in hg.edge_pins[e]:
                    b = part[u]
                    if b in tried:
                        continue
                    tried.add(b)
                    gain = remove_gain - sum(
                        hg.edge_weights[e2]
                        for e2 in hg.vertex_edges[v]
                        if pins_in[e2][b] == 0
                    )
                    if (
                        gain > best_gain
                        and block_w[b] + hg.vertex_weights[v] <= maxb
                    ):
                        best_gain = gain
                        best_b = b
            if best_b < 0:
                continue
            for e in hg.vertex_edges[v]:
                pins_in[e][a] -= 1
                pins_in[e][best_b] += 1
            block_w[a] -= hg.vertex_weights[v]
            block_w[best_b] += hg.vertex_weights[v]
            part[v] = best_b
            moved = True
        if not moved:
            break


def partition_kway(
    hg: Hypergraph,
    k: int,
    imbalance: float = 0.03,
    rng: random.Random | None = None,
    objective: str = "cut",
    refine_passes: int = 8,
) -> list[int]:
    """Recursive-bisection k-way partitioning (KaHyPar's RB mode).

    Dispatches to the native C++ partitioner when available (same
    algorithm family, much faster on large networks); this Python
    implementation is the oracle and fallback. ``objective='km1'``
    appends a direct k-way connectivity-refinement pass — the two
    presets the reference embeds as distinct KaHyPar configs.
    """
    if objective not in ("cut", "km1"):
        raise ValueError(f"unknown partition objective {objective!r}")
    if rng is None:
        rng = random.Random(42)

    from tnc_tpu.partitioning.native_binding import (
        native_kway_refine_km1,
        native_partition_kway,
    )

    native = native_partition_kway(hg, k, imbalance, rng.getrandbits(63))
    if native is not None:
        if objective == "km1":
            refined = native_kway_refine_km1(
                hg, native, k, imbalance, max_passes=refine_passes
            )
            if refined is not None:
                return refined
            kway_refine_km1(hg, native, k, imbalance, max_passes=refine_passes)
        return native

    part = [0] * hg.num_vertices

    def recurse(vertices: list[int], k_local: int, base: int) -> None:
        if k_local <= 1 or len(vertices) <= 1:
            for v in vertices:
                part[v] = base
            return
        k_left = k_local // 2
        k_right = k_local - k_left
        # build sub-hypergraph
        index = {v: i for i, v in enumerate(vertices)}
        sub_edges = []
        sub_weights = []
        for pins, w in zip(hg.edge_pins, hg.edge_weights):
            sub_pins = [index[v] for v in pins if v in index]
            if len(sub_pins) >= 2:
                sub_edges.append(sub_pins)
                sub_weights.append(w)
        sub = Hypergraph(
            len(vertices),
            [hg.vertex_weights[v] for v in vertices],
            sub_edges,
            sub_weights,
        )
        sides = bisect(
            sub, imbalance, rng, target_fraction=k_left / k_local
        )
        left = [v for v, s in zip(vertices, sides) if s == 0]
        right = [v for v, s in zip(vertices, sides) if s == 1]
        if not left or not right:  # degenerate split: force non-empty
            half = max(1, len(vertices) * k_left // k_local)
            left, right = vertices[:half], vertices[half:]
        recurse(left, k_left, base)
        recurse(right, k_right, base + k_left)

    recurse(list(range(hg.num_vertices)), k, 0)
    if objective == "km1":
        kway_refine_km1(hg, part, k, imbalance, max_passes=refine_passes)
    return part
