"""ctypes binding to the native C++ multilevel partitioner.

The shared library is compiled from ``native/partitioner.cpp`` on first
use (g++ -O3; rebuilt when the source is newer than the cached ``.so``)
and loaded with ctypes — the same "native partitioner behind a thin
binding" shape as the reference's ``kahypar`` crate wrapping the KaHyPar
C++ library. If no compiler is available the pure-Python implementation
in :mod:`tnc_tpu.partitioning.bisect` takes over transparently.

Set ``TNC_TPU_NO_NATIVE=1`` to force the Python path.
"""

from __future__ import annotations

import ctypes
import math
import os
import subprocess
import sys
import tempfile
from pathlib import Path

from tnc_tpu.partitioning.hypergraph import Hypergraph

_NATIVE_DIR = Path(__file__).parent / "native"
_SOURCES = [
    _NATIVE_DIR / "partitioner.cpp",
    _NATIVE_DIR / "treedp.cpp",
    _NATIVE_DIR / "slicereplay.cpp",
]
_SRC = _SOURCES[0]  # kept for back-compat with external callers
_LIB_PATH = _NATIVE_DIR / "_partitioner.so"

_lib: ctypes.CDLL | None = None
_load_failed = False


def _build_library() -> bool:
    """Compile the shared library; returns False when unavailable."""
    compiler = os.environ.get("CXX", "g++")
    # atomic replace so concurrent test workers don't race on a half-
    # written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_NATIVE_DIR))
    os.close(fd)
    cmd = [
        compiler,
        "-O3",
        "-march=native",
        "-std=c++17",
        "-shared",
        "-fPIC",
        *[str(s) for s in _SOURCES if s.exists()],
        "-o",
        tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=240)
        if proc.returncode != 0:
            # retry without -march=native (unsupported on some toolchains)
            cmd.remove("-march=native")
            proc = subprocess.run(cmd, capture_output=True, timeout=240)
        if proc.returncode != 0:
            print(
                f"tnc_tpu: native partitioner build failed:\n"
                f"{proc.stderr.decode(errors='replace')[-2000:]}",
                file=sys.stderr,
            )
            os.unlink(tmp)
            return False
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.TimeoutExpired):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_native() -> ctypes.CDLL | None:
    """The loaded library, building it if needed; None when unavailable."""
    global _lib, _load_failed
    if _load_failed or os.environ.get("TNC_TPU_NO_NATIVE"):
        return None
    if _lib is not None:
        return _lib
    try:
        sources = [s for s in _SOURCES if s.exists()]
        if sources:
            stale = not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < max(
                s.stat().st_mtime for s in sources
            )
        else:
            # source stripped from the install: use a prebuilt .so as-is
            stale = not _LIB_PATH.exists()
        if stale and not _build_library():
            _load_failed = True
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.tnc_partition_kway.restype = ctypes.c_int
        lib.tnc_partition_kway.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.tnc_cut_weight.restype = ctypes.c_double
        lib.tnc_cut_weight.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int),
        ]
        if hasattr(lib, "tnc_kway_refine_km1"):
            lib.tnc_kway_refine_km1.restype = ctypes.c_int
            lib.tnc_kway_refine_km1.argtypes = [
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.c_double,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.tnc_km1_weight.restype = ctypes.c_double
            lib.tnc_km1_weight.argtypes = [
                ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
            ]
        if hasattr(lib, "tnc_sliced_replay"):
            lib.tnc_sliced_replay.restype = ctypes.c_int
            lib.tnc_sliced_replay.argtypes = [
                ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
            ]
        if hasattr(lib, "tnc_optimal_order"):
            lib.tnc_optimal_order.restype = ctypes.c_int
            lib.tnc_optimal_order.argtypes = [
                ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.c_double,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int),
            ]
        _lib = lib
        return _lib
    except OSError:
        _load_failed = True
        return None


def native_partition_kway(
    hg: Hypergraph, k: int, imbalance: float, seed: int, trials: int = 4
) -> list[int] | None:
    """k-way partition via the C++ library; None when native is off.

    Runs ``trials`` seeded multi-starts and keeps the best cut (the
    native solver is ~12x faster per run than the Python fallback, so
    multi-start is still a large net win in both time and quality).
    """
    import numpy as np

    lib = load_native()
    if lib is None:
        return None
    n = hg.num_vertices
    m = len(hg.edge_pins)
    offsets = np.zeros(m + 1, dtype=np.int32)
    lengths = np.fromiter(
        (len(e) for e in hg.edge_pins), dtype=np.int32, count=m
    )
    np.cumsum(lengths, out=offsets[1:])
    pins = np.fromiter(
        (v for e in hg.edge_pins for v in e),
        dtype=np.int32,
        count=int(offsets[-1]),
    )
    vw = np.asarray(hg.vertex_weights, dtype=np.float64)
    ew = np.asarray(hg.edge_weights, dtype=np.float64)
    out = np.empty(n, dtype=np.int32)

    as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))  # noqa: E731
    as_f64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))  # noqa: E731

    best: "np.ndarray | None" = None
    best_cut = float("inf")
    for t in range(max(1, trials)):
        rc = lib.tnc_partition_kway(
            n, as_f64(vw), m, as_i32(offsets), as_i32(pins), as_f64(ew),
            k, ctypes.c_double(imbalance),
            ctypes.c_uint64((seed + 0x9E3779B97F4A7C15 * t) & (2**64 - 1)),
            as_i32(out),
        )
        if rc != 0:
            return None
        cut = lib.tnc_cut_weight(n, m, as_i32(offsets), as_i32(pins), as_f64(ew), as_i32(out))
        if cut < best_cut:
            best_cut = cut
            best = out.copy()
        out = np.empty(n, dtype=np.int32)
    assert best is not None
    return best.tolist()


def _csr_arrays(hg: Hypergraph):
    import numpy as np

    m = len(hg.edge_pins)
    offsets = np.zeros(m + 1, dtype=np.int32)
    lengths = np.fromiter(
        (len(e) for e in hg.edge_pins), dtype=np.int32, count=m
    )
    np.cumsum(lengths, out=offsets[1:])
    pins = np.fromiter(
        (v for e in hg.edge_pins for v in e),
        dtype=np.int32,
        count=int(offsets[-1]),
    )
    vw = np.asarray(hg.vertex_weights, dtype=np.float64)
    ew = np.asarray(hg.edge_weights, dtype=np.float64)
    return offsets, pins, vw, ew


def native_kway_refine_km1(
    hg: Hypergraph,
    part: "list[int]",
    k: int,
    imbalance: float,
    max_passes: int = 8,
) -> list[int] | None:
    """km1 (connectivity) k-way refinement via the C++ library; returns
    the refined partition, or None when native is off/outdated."""
    import numpy as np

    lib = load_native()
    if lib is None or not hasattr(lib, "tnc_kway_refine_km1"):
        return None
    offsets, pins, vw, ew = _csr_arrays(hg)
    buf = np.asarray(part, dtype=np.int32).copy()
    as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))  # noqa: E731
    as_f64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))  # noqa: E731
    rc = lib.tnc_kway_refine_km1(
        hg.num_vertices, as_f64(vw), len(hg.edge_pins), as_i32(offsets),
        as_i32(pins), as_f64(ew), k, ctypes.c_double(imbalance),
        int(max_passes), as_i32(buf),
    )
    if rc != 0:
        return None
    return buf.tolist()


def native_km1_weight(
    hg: Hypergraph, part: "list[int]", k: int
) -> float | None:
    """km1 (connectivity) metric via the C++ library; None when native
    is off/outdated or the partition is invalid (values outside 0..k)."""
    import numpy as np

    lib = load_native()
    if lib is None or not hasattr(lib, "tnc_km1_weight"):
        return None
    offsets, pins, _vw, ew = _csr_arrays(hg)
    buf = np.asarray(part, dtype=np.int32)
    as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))  # noqa: E731
    as_f64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))  # noqa: E731
    out = float(
        lib.tnc_km1_weight(
            hg.num_vertices, len(hg.edge_pins), as_i32(offsets), as_i32(pins),
            as_f64(ew), k, as_i32(buf),
        )
    )
    return None if out < 0 else out


class SlicedReplayer:
    """Reusable native replayer over one (inputs, path) pair.

    Precomputes bitmask leg sets and the dense leg index once; each
    ``sizes``/``flops`` call replays the path with a different removed
    set in C++ (``native/slicereplay.cpp``) — the planner's hottest loop
    (slicing-aware candidate scoring calls it thousands of times per
    plan; ~96% of north-star planning time in Python).
    ``available`` is False when the native library is off — callers keep
    their Python loops as oracle/fallback.
    """

    def __init__(self, inputs, replace_path):
        import numpy as np

        self._lib = load_native()
        # degenerate instances (no leaves / empty path) stay on the
        # Python oracle, which defines their behavior (peak 0.0)
        self.available = (
            self._lib is not None
            and hasattr(self._lib, "tnc_sliced_replay")
            and len(inputs) > 0
            and len(replace_path) > 0
        )
        if not self.available:
            return
        legs = sorted({leg for t in inputs for leg in t.legs})
        self._leg_index = {leg: i for i, leg in enumerate(legs)}
        self._legs = legs
        n_words = max(1, (len(legs) + 63) // 64)
        self._n_words = n_words
        self._masks = np.zeros((len(inputs), n_words), dtype=np.uint64)
        self._log2dims = np.zeros(n_words * 64, dtype=np.float64)
        for t_i, t in enumerate(inputs):
            for leg, dim in t.edges():
                i = self._leg_index[leg]
                self._masks[t_i, i // 64] |= np.uint64(1 << (i % 64))
                self._log2dims[i] = float(np.log2(max(1, dim)))
        self._pairs = np.asarray(replace_path, dtype=np.int32).reshape(-1)
        self._n_leaves = len(inputs)
        self._n_steps = len(replace_path)

    def _removed_mask(self, removed):
        import numpy as np

        mask = np.zeros(self._n_words, dtype=np.uint64)
        for leg in removed:
            i = self._leg_index.get(leg)
            if i is not None:
                mask[i // 64] |= np.uint64(1 << (i % 64))
        return mask

    def _call(self, removed, want_leg_peak: bool):
        import numpy as np

        as_u64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))  # noqa: E731
        as_f64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))  # noqa: E731
        as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))  # noqa: E731
        rm = self._removed_mask(removed)
        peak = ctypes.c_double(0.0)
        flops = ctypes.c_double(0.0)
        leg_peak = (
            np.zeros(self._n_words * 64, dtype=np.float64)
            if want_leg_peak
            else None
        )
        rc = self._lib.tnc_sliced_replay(
            self._n_leaves,
            self._n_words,
            as_u64(self._masks),
            as_f64(self._log2dims),
            self._n_steps,
            as_i32(self._pairs),
            as_u64(rm),
            ctypes.byref(peak),
            ctypes.byref(flops),
            as_f64(leg_peak) if leg_peak is not None else None,
        )
        if rc != 0:
            raise ValueError("tnc_sliced_replay rejected the path")
        return float(peak.value), float(flops.value), leg_peak

    def sizes(self, removed) -> tuple[float, dict[int, float]]:
        """(peak step size, leg -> largest participating step size) —
        the native ``_replay_sizes``."""
        peak, _flops, leg_peak = self._call(removed, want_leg_peak=True)
        out = {
            self._legs[i]: float(v)
            for i, v in enumerate(leg_peak[: len(self._legs)])
            if v > 0.0
        }
        return peak, out

    def flops(self, removed) -> float:
        """Total union-size op cost — the native ``_reduced_flops``."""
        _peak, flops, _ = self._call(removed, want_leg_peak=False)
        return flops

    def peak_and_flops(self, removed) -> tuple[float, float]:
        """Both metrics from a single replay (candidate-leg scoring
        needs both; one native call instead of two)."""
        peak, flops, _ = self._call(removed, want_leg_peak=False)
        return peak, flops

    def peak(self, removed) -> float:
        """Peak step size only (acceptance checks)."""
        peak, _flops, _ = self._call(removed, want_leg_peak=False)
        return peak


def native_optimal_order(
    leg_sets: "list[frozenset[int]]",
    dims: "dict[int, int]",
    minimize: str = "flops",
    logsize_cap: float = -1.0,
) -> tuple[float, list[tuple[int, int]]] | None:
    """Exact subset-DP ordering over ``leg_sets`` via the C++ kernel.

    Native engine of ``ContractionTree.reconfigure``; returns
    (cost, local ssa pairs) like the Python ``_optimal_order``;
    ``(inf, [])`` when the DP *proved* no ordering satisfies
    ``logsize_cap`` (callers must not fall back to the Python DP — it
    would only reproduce the proof slowly); None when native is
    unavailable or n is out of range.
    """
    import numpy as np

    lib = load_native()
    n = len(leg_sets)
    if lib is None or not hasattr(lib, "tnc_optimal_order") or not 2 <= n <= 16:
        return None
    all_legs = sorted(set().union(*leg_sets))
    index = {leg: i for i, leg in enumerate(all_legs)}
    nlegs = len(all_legs)
    nwords = max(1, (nlegs + 63) // 64)
    masks = np.zeros((n, nwords), dtype=np.uint64)
    for i, legs in enumerate(leg_sets):
        for leg in legs:
            j = index[leg]
            masks[i, j // 64] |= np.uint64(1 << (j % 64))
    logdims = np.array(
        [math.log2(max(1, dims[leg])) for leg in all_legs], dtype=np.float64
    )
    out_cost = ctypes.c_double(0.0)
    out_pairs = np.empty(2 * (n - 1), dtype=np.int32)
    rc = lib.tnc_optimal_order(
        n,
        nlegs,
        masks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        logdims.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        0 if minimize == "flops" else 1,
        ctypes.c_double(logsize_cap),
        ctypes.byref(out_cost),
        out_pairs.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
    )
    if rc == 1:
        return math.inf, []
    if rc != 0:
        return None
    pairs = [
        (int(out_pairs[2 * k]), int(out_pairs[2 * k + 1])) for k in range(n - 1)
    ]
    return float(out_cost.value), pairs
