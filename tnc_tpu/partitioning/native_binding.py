"""ctypes binding to the native C++ multilevel partitioner.

The shared library is compiled from ``native/partitioner.cpp`` on first
use (g++ -O3; rebuilt when the source is newer than the cached ``.so``)
and loaded with ctypes — the same "native partitioner behind a thin
binding" shape as the reference's ``kahypar`` crate wrapping the KaHyPar
C++ library. If no compiler is available the pure-Python implementation
in :mod:`tnc_tpu.partitioning.bisect` takes over transparently.

Set ``TNC_TPU_NO_NATIVE=1`` to force the Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
from pathlib import Path

from tnc_tpu.partitioning.hypergraph import Hypergraph

_NATIVE_DIR = Path(__file__).parent / "native"
_SRC = _NATIVE_DIR / "partitioner.cpp"
_LIB_PATH = _NATIVE_DIR / "_partitioner.so"

_lib: ctypes.CDLL | None = None
_load_failed = False


def _build_library() -> bool:
    """Compile the shared library; returns False when unavailable."""
    compiler = os.environ.get("CXX", "g++")
    # atomic replace so concurrent test workers don't race on a half-
    # written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_NATIVE_DIR))
    os.close(fd)
    cmd = [
        compiler,
        "-O3",
        "-march=native",
        "-std=c++17",
        "-shared",
        "-fPIC",
        str(_SRC),
        "-o",
        tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=240)
        if proc.returncode != 0:
            # retry without -march=native (unsupported on some toolchains)
            cmd.remove("-march=native")
            proc = subprocess.run(cmd, capture_output=True, timeout=240)
        if proc.returncode != 0:
            print(
                f"tnc_tpu: native partitioner build failed:\n"
                f"{proc.stderr.decode(errors='replace')[-2000:]}",
                file=sys.stderr,
            )
            os.unlink(tmp)
            return False
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.TimeoutExpired):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_native() -> ctypes.CDLL | None:
    """The loaded library, building it if needed; None when unavailable."""
    global _lib, _load_failed
    if _load_failed or os.environ.get("TNC_TPU_NO_NATIVE"):
        return None
    if _lib is not None:
        return _lib
    try:
        if _SRC.exists():
            stale = (
                not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime
            )
        else:
            # source stripped from the install: use a prebuilt .so as-is
            stale = not _LIB_PATH.exists()
        if stale and not _build_library():
            _load_failed = True
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.tnc_partition_kway.restype = ctypes.c_int
        lib.tnc_partition_kway.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.tnc_cut_weight.restype = ctypes.c_double
        lib.tnc_cut_weight.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int),
        ]
        _lib = lib
        return _lib
    except OSError:
        _load_failed = True
        return None


def native_partition_kway(
    hg: Hypergraph, k: int, imbalance: float, seed: int, trials: int = 4
) -> list[int] | None:
    """k-way partition via the C++ library; None when native is off.

    Runs ``trials`` seeded multi-starts and keeps the best cut (the
    native solver is ~12x faster per run than the Python fallback, so
    multi-start is still a large net win in both time and quality).
    """
    import numpy as np

    lib = load_native()
    if lib is None:
        return None
    n = hg.num_vertices
    m = len(hg.edge_pins)
    offsets = np.zeros(m + 1, dtype=np.int32)
    lengths = np.fromiter(
        (len(e) for e in hg.edge_pins), dtype=np.int32, count=m
    )
    np.cumsum(lengths, out=offsets[1:])
    pins = np.fromiter(
        (v for e in hg.edge_pins for v in e),
        dtype=np.int32,
        count=int(offsets[-1]),
    )
    vw = np.asarray(hg.vertex_weights, dtype=np.float64)
    ew = np.asarray(hg.edge_weights, dtype=np.float64)
    out = np.empty(n, dtype=np.int32)

    as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))  # noqa: E731
    as_f64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))  # noqa: E731

    best: "np.ndarray | None" = None
    best_cut = float("inf")
    for t in range(max(1, trials)):
        rc = lib.tnc_partition_kway(
            n, as_f64(vw), m, as_i32(offsets), as_i32(pins), as_f64(ew),
            k, ctypes.c_double(imbalance),
            ctypes.c_uint64((seed + 0x9E3779B97F4A7C15 * t) & (2**64 - 1)),
            as_i32(out),
        )
        if rc != 0:
            return None
        cut = lib.tnc_cut_weight(n, m, as_i32(offsets), as_i32(pins), as_f64(ew), as_i32(out))
        if cut < best_cut:
            best_cut = cut
            best = out.copy()
        out = np.empty(n, dtype=np.int32)
    assert best is not None
    return best.tolist()
