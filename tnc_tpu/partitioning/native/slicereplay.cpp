// Native sliced-path replay — the planner's hottest loop.
//
// Slicing-aware candidate scoring replays a contraction path once per
// candidate leg with that leg's dimension pinned to 1
// (contractionpath/slicing.py::_replay_sizes/_reduced_flops). In Python
// this builds millions of throwaway LeafTensors (96% of north-star
// planning time, ~230 s of 240 s profiled); here a replay is a few
// hundred bitset XORs. Leg sets are bitmasks over dense leg indices
// (n_words x u64, same shape discipline as treedp.cpp); sizes are
// 2^(sum of log2 dims over set bits), matching the Python cost model
// exactly (it computes in float products of power-of-two dims).
//
// Exposed through the same ctypes binding as the partitioner.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

inline double mask_log2size(const uint64_t* mask, int n_words,
                            const double* log2dims) {
    double s = 0.0;
    for (int w = 0; w < n_words; ++w) {
        uint64_t bits = mask[w];
        while (bits) {
            int b = __builtin_ctzll(bits);
            s += log2dims[w * 64 + b];
            bits &= bits - 1;
        }
    }
    return s;
}

}  // namespace

extern "C" {

// Replay a flat replace-format path over bitmask leg sets with
// `removed_mask` legs deleted everywhere.
//
//   leaf_masks: n_leaves * n_words u64, leg bit i set = tensor has leg i
//   log2dims:   n_words*64 doubles (log2 of each leg's dim; 0 padding)
//   pairs:      2*n_steps ints, replace-left (result overwrites slot i)
//   out_peak:   max over steps of (|out| + |in1| + |in2|) in elements
//   out_flops:  sum over steps of |in1 UNION in2| in elements
//   out_leg_peak: if non-null, n_words*64 doubles — for every leg, the
//                 largest step size any tensor holding it participated
//                 in (0 = never seen); mirrors _replay_sizes' map.
//
// Returns 0 on success, 1 on malformed input.
int tnc_sliced_replay(int n_leaves, int n_words, const uint64_t* leaf_masks,
                      const double* log2dims, int n_steps, const int* pairs,
                      const uint64_t* removed_mask, double* out_peak,
                      double* out_flops, double* out_leg_peak) {
    if (n_leaves <= 0 || n_words <= 0 || n_steps < 0) return 1;
    std::vector<uint64_t> masks((size_t)n_leaves * n_words);
    for (int t = 0; t < n_leaves; ++t)
        for (int w = 0; w < n_words; ++w)
            masks[(size_t)t * n_words + w] =
                leaf_masks[(size_t)t * n_words + w] & ~removed_mask[w];

    std::vector<double> log2size(n_leaves);
    for (int t = 0; t < n_leaves; ++t)
        log2size[t] =
            mask_log2size(&masks[(size_t)t * n_words], n_words, log2dims);

    if (out_leg_peak)
        for (int i = 0; i < n_words * 64; ++i) out_leg_peak[i] = 0.0;

    double peak = 0.0, flops = 0.0;
    std::vector<uint64_t> un(n_words);
    for (int s = 0; s < n_steps; ++s) {
        int i = pairs[2 * s], j = pairs[2 * s + 1];
        if (i < 0 || i >= n_leaves || j < 0 || j >= n_leaves || i == j)
            return 1;
        uint64_t* mi = &masks[(size_t)i * n_words];
        uint64_t* mj = &masks[(size_t)j * n_words];
        for (int w = 0; w < n_words; ++w) un[w] = mi[w] | mj[w];
        double lun = mask_log2size(un.data(), n_words, log2dims);
        flops += std::exp2(lun);
        // out = i ^ j; contracted legs are in both (i & j)
        double lshared = 0.0;
        for (int w = 0; w < n_words; ++w) {
            uint64_t shared = mi[w] & mj[w];
            while (shared) {
                int b = __builtin_ctzll(shared);
                lshared += log2dims[w * 64 + b];
                shared &= shared - 1;
            }
        }
        double lout = lun - lshared;  // xor = union minus shared legs
        double step = std::exp2(lout) + std::exp2(log2size[i]) +
                      std::exp2(log2size[j]);
        if (step > peak) peak = step;
        if (out_leg_peak) {
            // legs of in1, in2, out are all subsets of the union
            for (int w = 0; w < n_words; ++w) {
                uint64_t bits = un[w];
                while (bits) {
                    int b = __builtin_ctzll(bits);
                    int leg = w * 64 + b;
                    if (step > out_leg_peak[leg]) out_leg_peak[leg] = step;
                    bits &= bits - 1;
                }
            }
        }
        for (int w = 0; w < n_words; ++w) mi[w] ^= mj[w];
        log2size[i] = lout;
        // slot j is consumed (replace-left); leave its mask, it is
        // never referenced again on a valid path
    }
    *out_peak = peak;
    *out_flops = flops;
    return 0;
}

}  // extern "C"
