// Native multilevel hypergraph partitioner (KaHyPar-class).
//
// The reference links the KaHyPar C++ library for its partitioning step
// (tnc/src/tensornetwork/partitioning.rs:6,76-89). This is an original
// multilevel implementation of the same algorithm family — heavy-edge
// matching coarsening, BFS region-growing initial partitions, and
// Fiduccia–Mattheyses refinement at every uncoarsening level, with k-way
// via recursive bisection — exposed through a C ABI for ctypes.
//
// Deterministic for a fixed seed (own mt19937_64; no global state).

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <deque>
#include <queue>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Hypergraph {
    int n = 0;
    std::vector<double> vertex_weights;
    std::vector<std::vector<int>> edge_pins;
    std::vector<double> edge_weights;
    std::vector<std::vector<int>> vertex_edges;

    void build_incidence() {
        vertex_edges.assign(n, {});
        for (int e = 0; e < (int)edge_pins.size(); ++e)
            for (int v : edge_pins[e]) vertex_edges[v].push_back(e);
    }

    double total_vertex_weight() const {
        double s = 0;
        for (double w : vertex_weights) s += w;
        return s;
    }

    double cut_weight(const std::vector<int>& part) const {
        double cut = 0;
        for (int e = 0; e < (int)edge_pins.size(); ++e) {
            int first = part[edge_pins[e][0]];
            for (int v : edge_pins[e])
                if (part[v] != first) {
                    cut += edge_weights[e];
                    break;
                }
        }
        return cut;
    }
};

struct CoarseLevel {
    Hypergraph graph;
    std::vector<std::vector<int>> members;  // coarse vertex -> fine vertices
};

// One round of heavy-edge matching; false = no progress.
bool coarsen_once(const Hypergraph& hg, std::mt19937_64& rng, CoarseLevel& out) {
    const int n = hg.n;
    std::vector<int> order(n);
    for (int i = 0; i < n; ++i) order[i] = i;
    std::shuffle(order.begin(), order.end(), rng);

    std::vector<int> matched(n, -1);
    std::unordered_map<int, double> conn;
    for (int v : order) {
        if (matched[v] >= 0) continue;
        conn.clear();
        for (int e : hg.vertex_edges[v]) {
            const auto& pins = hg.edge_pins[e];
            if ((int)pins.size() > 8) continue;  // skip huge hyperedges
            double w = hg.edge_weights[e] / (double)(pins.size() - 1);
            for (int u : pins)
                if (u != v && matched[u] < 0) conn[u] += w;
        }
        int best_u = -1;
        double best_w = 0.0;
        for (const auto& [u, w] : conn)
            if (w > best_w || (w == best_w && best_u >= 0 && u < best_u)) {
                best_w = w;
                best_u = u;
            }
        if (best_u >= 0) {
            matched[v] = best_u;
            matched[best_u] = v;
        }
    }

    std::vector<int> coarse_id(n, -1);
    out.members.clear();
    for (int v = 0; v < n; ++v) {
        if (coarse_id[v] >= 0) continue;
        int u = matched[v];
        int cid = (int)out.members.size();
        if (u >= 0 && u != v) {
            out.members.push_back({v, u});
            coarse_id[v] = coarse_id[u] = cid;
        } else {
            out.members.push_back({v});
            coarse_id[v] = cid;
        }
    }
    if ((int)out.members.size() >= n) return false;

    Hypergraph& cg = out.graph;
    cg.n = (int)out.members.size();
    cg.vertex_weights.assign(cg.n, 0.0);
    for (int cid = 0; cid < cg.n; ++cid)
        for (int v : out.members[cid]) cg.vertex_weights[cid] += hg.vertex_weights[v];

    // merge parallel coarse hyperedges, keyed by sorted pin set
    std::unordered_map<std::string, int> edge_index;
    std::vector<int> cpins;
    for (int e = 0; e < (int)hg.edge_pins.size(); ++e) {
        cpins.clear();
        for (int v : hg.edge_pins[e]) cpins.push_back(coarse_id[v]);
        std::sort(cpins.begin(), cpins.end());
        cpins.erase(std::unique(cpins.begin(), cpins.end()), cpins.end());
        if ((int)cpins.size() < 2) continue;
        std::string key((const char*)cpins.data(), cpins.size() * sizeof(int));
        auto it = edge_index.find(key);
        if (it == edge_index.end()) {
            edge_index.emplace(std::move(key), (int)cg.edge_pins.size());
            cg.edge_pins.push_back(cpins);
            cg.edge_weights.push_back(hg.edge_weights[e]);
        } else {
            cg.edge_weights[it->second] += hg.edge_weights[e];
        }
    }
    cg.build_incidence();
    return true;
}

// BFS region growing from random seeds; best cut over `attempts` wins.
std::vector<int> initial_partition(const Hypergraph& hg, double target0,
                                   double imbalance, std::mt19937_64& rng,
                                   int attempts = 8) {
    std::vector<int> best;
    double best_cut = 1e300;
    const double max0 = target0 * (1.0 + imbalance);
    std::uniform_int_distribution<int> pick(0, hg.n - 1);
    for (int a = 0; a < std::max(1, attempts); ++a) {
        std::vector<int> part(hg.n, 1);
        int seed = pick(rng);
        double weight0 = 0.0;
        std::deque<int> frontier{seed};
        std::vector<char> seen(hg.n, 0);
        seen[seed] = 1;
        while (!frontier.empty() && weight0 < target0) {
            int v = frontier.back();
            frontier.pop_back();
            if (weight0 + hg.vertex_weights[v] > max0) continue;
            part[v] = 0;
            weight0 += hg.vertex_weights[v];
            for (int e : hg.vertex_edges[v])
                for (int u : hg.edge_pins[e])
                    if (!seen[u]) {
                        seen[u] = 1;
                        frontier.push_front(u);
                    }
        }
        double cut = hg.cut_weight(part);
        if (cut < best_cut) {
            best_cut = cut;
            best = part;
        }
    }
    return best;
}

// Fiduccia–Mattheyses boundary refinement, in place.
void fm_refine(const Hypergraph& hg, std::vector<int>& part, double target0,
               double imbalance, int max_passes = 8) {
    const int n = hg.n;
    const double total = hg.total_vertex_weight();
    const double min0 = target0 * (1.0 - imbalance);
    const double max0 = target0 * (1.0 + imbalance);

    std::vector<std::array<int, 2>> pins_in(hg.edge_pins.size());
    for (int pass = 0; pass < max_passes; ++pass) {
        for (int e = 0; e < (int)hg.edge_pins.size(); ++e) {
            pins_in[e] = {0, 0};
            for (int v : hg.edge_pins[e]) pins_in[e][part[v]]++;
        }
        double weight0 = 0.0;
        for (int v = 0; v < n; ++v)
            if (part[v] == 0) weight0 += hg.vertex_weights[v];

        auto gain = [&](int v) {
            double g = 0.0;
            int side = part[v], other = 1 - side;
            for (int e : hg.vertex_edges[v]) {
                if (pins_in[e][side] == 1) g += hg.edge_weights[e];
                if (pins_in[e][other] == 0) g -= hg.edge_weights[e];
            }
            return g;
        };

        // max-heap of (gain, vertex); lazy deletion via gain re-check
        std::priority_queue<std::pair<double, int>> heap;
        for (int v = 0; v < n; ++v) heap.push({gain(v), v});

        std::vector<char> locked(n, 0);
        std::vector<int> moves;
        double cum_gain = 0.0, best_gain = 0.0;
        size_t best_prefix = 0;

        while (!heap.empty()) {
            auto [g_stored, v] = heap.top();
            heap.pop();
            if (locked[v]) continue;
            double g = gain(v);
            if (g_stored != g) {  // stale entry: reinsert fresh
                heap.push({g, v});
                continue;
            }
            double w = hg.vertex_weights[v];
            double new_weight0 = part[v] == 0 ? weight0 - w : weight0 + w;
            if (!(min0 <= new_weight0 && new_weight0 <= max0) && total > w) {
                locked[v] = 1;
                continue;
            }
            int side = part[v];
            for (int e : hg.vertex_edges[v]) {
                pins_in[e][side]--;
                pins_in[e][1 - side]++;
            }
            part[v] = 1 - side;
            weight0 = new_weight0;
            locked[v] = 1;
            cum_gain += g;
            moves.push_back(v);
            if (cum_gain > best_gain + 1e-12) {
                best_gain = cum_gain;
                best_prefix = moves.size();
            }
            for (int e : hg.vertex_edges[v])
                for (int u : hg.edge_pins[e])
                    if (!locked[u]) heap.push({gain(u), u});
        }

        for (size_t i = best_prefix; i < moves.size(); ++i)
            part[moves[i]] = 1 - part[moves[i]];
        if (best_gain <= 1e-12) break;
    }
}

std::vector<int> bisect(const Hypergraph& hg, double imbalance,
                        std::mt19937_64& rng, double target_fraction = 0.5,
                        int coarsen_to = 80) {
    if (hg.n <= 1) return std::vector<int>(hg.n, 0);
    double target0 = hg.total_vertex_weight() * target_fraction;

    std::vector<CoarseLevel> levels;
    const Hypergraph* current = &hg;
    while (current->n > coarsen_to) {
        CoarseLevel level;
        if (!coarsen_once(*current, rng, level)) break;
        levels.push_back(std::move(level));
        current = &levels.back().graph;
    }

    std::vector<int> part = initial_partition(*current, target0, imbalance, rng);
    fm_refine(*current, part, target0, imbalance);

    for (int i = (int)levels.size() - 1; i >= 0; --i) {
        const Hypergraph& fine = i == 0 ? hg : levels[i - 1].graph;
        std::vector<int> fine_part(fine.n, 0);
        for (int cid = 0; cid < (int)levels[i].members.size(); ++cid)
            for (int v : levels[i].members[cid]) fine_part[v] = part[cid];
        part = std::move(fine_part);
        fm_refine(fine, part, target0, imbalance);
    }
    return part;
}

void partition_recurse(const Hypergraph& hg, const std::vector<int>& vertices,
                       int k_local, int base, double imbalance,
                       std::mt19937_64& rng, std::vector<int>& part) {
    if (k_local <= 1 || (int)vertices.size() <= 1) {
        for (int v : vertices) part[v] = base;
        return;
    }
    int k_left = k_local / 2;
    int k_right = k_local - k_left;

    std::vector<int> index(hg.n, -1);
    for (int i = 0; i < (int)vertices.size(); ++i) index[vertices[i]] = i;

    Hypergraph sub;
    sub.n = (int)vertices.size();
    sub.vertex_weights.reserve(sub.n);
    for (int v : vertices) sub.vertex_weights.push_back(hg.vertex_weights[v]);
    std::vector<int> sub_pins;
    for (int e = 0; e < (int)hg.edge_pins.size(); ++e) {
        sub_pins.clear();
        for (int v : hg.edge_pins[e])
            if (index[v] >= 0) sub_pins.push_back(index[v]);
        if ((int)sub_pins.size() >= 2) {
            sub.edge_pins.push_back(sub_pins);
            sub.edge_weights.push_back(hg.edge_weights[e]);
        }
    }
    sub.build_incidence();

    std::vector<int> sides =
        bisect(sub, imbalance, rng, (double)k_left / (double)k_local);
    std::vector<int> left, right;
    for (int i = 0; i < (int)vertices.size(); ++i)
        (sides[i] == 0 ? left : right).push_back(vertices[i]);
    if (left.empty() || right.empty()) {  // degenerate split: force non-empty
        left.clear();
        right.clear();
        size_t half = std::max<size_t>(
            1, vertices.size() * (size_t)k_left / (size_t)k_local);
        for (size_t i = 0; i < vertices.size(); ++i)
            (i < half ? left : right).push_back(vertices[i]);
    }
    partition_recurse(hg, left, k_left, base, imbalance, rng, part);
    partition_recurse(hg, right, k_right, base + k_left, imbalance, rng, part);
}

// Direct k-way move-based refinement under the connectivity (km1)
// objective: sum_e w_e * (lambda_e - 1), lambda_e = #blocks edge e
// touches. This is where the km1 preset genuinely diverges from
// cut-based recursive bisection — in any 2-way split lambda-1 equals
// the cut indicator, so only a k-way pass can tell them apart (the
// same reason KaHyPar ships cut and km1 as distinct configs,
// tnc/src/tensornetwork/partition_config.rs:12-36).
void kway_refine_km1(const Hypergraph& hg, std::vector<int>& part, int k,
                     double imbalance, int max_passes = 8) {
    const int n = hg.n;
    if (k <= 1 || n <= 1) return;
    const double target = hg.total_vertex_weight() / (double)k;
    const double maxb = target * (1.0 + imbalance);

    std::vector<std::vector<int>> pins_in(hg.edge_pins.size(),
                                          std::vector<int>(k, 0));
    for (int e = 0; e < (int)hg.edge_pins.size(); ++e)
        for (int v : hg.edge_pins[e]) pins_in[e][part[v]]++;
    std::vector<double> block_w(k, 0.0);
    for (int v = 0; v < n; ++v) block_w[part[v]] += hg.vertex_weights[v];

    std::vector<char> tried(k, 0);
    for (int pass = 0; pass < max_passes; ++pass) {
        bool moved = false;
        for (int v = 0; v < n; ++v) {
            const int a = part[v];
            // candidate target blocks: only blocks adjacent through v's
            // edges can have positive gain
            double remove_gain = 0.0;
            for (int e : hg.vertex_edges[v])
                if (pins_in[e][a] == 1) remove_gain += hg.edge_weights[e];
            int best_b = -1;
            double best_gain = 1e-12;
            std::fill(tried.begin(), tried.end(), 0);
            tried[a] = 1;
            for (int e : hg.vertex_edges[v]) {
                for (int u : hg.edge_pins[e]) {
                    int b = part[u];
                    if (tried[b]) continue;
                    tried[b] = 1;
                    double gain = remove_gain;
                    for (int e2 : hg.vertex_edges[v])
                        if (pins_in[e2][b] == 0) gain -= hg.edge_weights[e2];
                    if (gain > best_gain &&
                        block_w[b] + hg.vertex_weights[v] <= maxb) {
                        best_gain = gain;
                        best_b = b;
                    }
                }
            }
            if (best_b < 0) continue;
            for (int e : hg.vertex_edges[v]) {
                pins_in[e][a]--;
                pins_in[e][best_b]++;
            }
            block_w[a] -= hg.vertex_weights[v];
            block_w[best_b] += hg.vertex_weights[v];
            part[v] = best_b;
            moved = true;
        }
        if (!moved) break;
    }
}

double km1_weight(const Hypergraph& hg, const std::vector<int>& part, int k) {
    double total = 0.0;
    std::vector<char> seen(k, 0);
    for (int e = 0; e < (int)hg.edge_pins.size(); ++e) {
        std::fill(seen.begin(), seen.end(), 0);
        int lambda = 0;
        for (int v : hg.edge_pins[e])
            if (!seen[part[v]]) {
                seen[part[v]] = 1;
                ++lambda;
            }
        if (lambda > 1) total += hg.edge_weights[e] * (double)(lambda - 1);
    }
    return total;
}

Hypergraph hypergraph_from_csr(int num_vertices, const double* vertex_weights,
                               int num_edges, const int* edge_offsets,
                               const int* edge_pins,
                               const double* edge_weights, bool* ok) {
    Hypergraph hg;
    *ok = false;
    if (num_vertices < 0 || num_edges < 0) return hg;
    hg.n = num_vertices;
    hg.vertex_weights.assign(vertex_weights, vertex_weights + num_vertices);
    hg.edge_pins.resize(num_edges);
    hg.edge_weights.assign(edge_weights, edge_weights + num_edges);
    for (int e = 0; e < num_edges; ++e) {
        int beg = edge_offsets[e], end = edge_offsets[e + 1];
        if (beg > end) return hg;
        hg.edge_pins[e].assign(edge_pins + beg, edge_pins + end);
        for (int v : hg.edge_pins[e])
            if (v < 0 || v >= num_vertices) return hg;
    }
    hg.build_incidence();
    *ok = true;
    return hg;
}

}  // namespace

extern "C" {

// Partition a hypergraph (CSR pin lists) into k blocks. Returns 0 on
// success; out_partition[v] in [0, k).
int tnc_partition_kway(int num_vertices, const double* vertex_weights,
                       int num_edges, const int* edge_offsets,
                       const int* edge_pins, const double* edge_weights,
                       int k, double imbalance, uint64_t seed,
                       int* out_partition) {
    if (k <= 0) return 1;
    bool ok = false;
    Hypergraph hg = hypergraph_from_csr(num_vertices, vertex_weights,
                                        num_edges, edge_offsets, edge_pins,
                                        edge_weights, &ok);
    if (!ok) return 1;

    std::mt19937_64 rng(seed);
    std::vector<int> part(num_vertices, 0);
    if (k > 1) {
        std::vector<int> vertices(num_vertices);
        for (int i = 0; i < num_vertices; ++i) vertices[i] = i;
        partition_recurse(hg, vertices, k, 0, imbalance, rng, part);
    }
    std::memcpy(out_partition, part.data(), num_vertices * sizeof(int));
    return 0;
}

// Refine a k-way partition in place under the km1 (connectivity)
// objective. `partition` is read and overwritten.
int tnc_kway_refine_km1(int num_vertices, const double* vertex_weights,
                        int num_edges, const int* edge_offsets,
                        const int* edge_pins, const double* edge_weights,
                        int k, double imbalance, int max_passes,
                        int* partition) {
    if (k <= 0) return 1;
    bool ok = false;
    Hypergraph hg = hypergraph_from_csr(num_vertices, vertex_weights,
                                        num_edges, edge_offsets, edge_pins,
                                        edge_weights, &ok);
    if (!ok) return 1;
    std::vector<int> part(partition, partition + num_vertices);
    for (int v : part)
        if (v < 0 || v >= k) return 1;
    kway_refine_km1(hg, part, k, imbalance, max_passes);
    std::memcpy(partition, part.data(), num_vertices * sizeof(int));
    return 0;
}

// km1 (connectivity) metric of a partition: sum_e w_e * (lambda_e - 1).
double tnc_km1_weight(int num_vertices, int num_edges,
                      const int* edge_offsets, const int* edge_pins,
                      const double* edge_weights, int k,
                      const int* partition) {
    bool ok = false;
    std::vector<double> unit(num_vertices, 1.0);
    Hypergraph hg = hypergraph_from_csr(num_vertices, unit.data(), num_edges,
                                        edge_offsets, edge_pins, edge_weights,
                                        &ok);
    if (!ok || k <= 0) return -1.0;
    std::vector<int> part(partition, partition + num_vertices);
    for (int v : part)
        if (v < 0 || v >= k) return -1.0;  // would index past seen[k]
    return km1_weight(hg, part, k);
}

// Cut weight of a given partition (for tests/diagnostics).
double tnc_cut_weight(int num_vertices, int num_edges, const int* edge_offsets,
                      const int* edge_pins, const double* edge_weights,
                      const int* partition) {
    double cut = 0.0;
    for (int e = 0; e < num_edges; ++e) {
        int beg = edge_offsets[e], end = edge_offsets[e + 1];
        if (end - beg < 2) continue;
        int first = partition[edge_pins[beg]];
        for (int i = beg + 1; i < end; ++i)
            if (partition[edge_pins[i]] != first) {
                cut += edge_weights[e];
                break;
            }
    }
    return cut;
}

}  // extern "C"
