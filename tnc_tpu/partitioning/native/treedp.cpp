// Exact subset-DP contraction ordering over a small tensor frontier.
//
// Native engine behind ContractionTree.reconfigure (the framework's
// equivalent of the reference's cotengra subtree_reconfigure bridge,
// tnc/src/contractionpath/paths/tree_reconfiguration.rs:54-56). The DP is
// the standard optimal-einsum recurrence over vertex subsets; legs are bit
// positions in multi-word masks and a leg appears in at most two tensors,
// so the result legs of any subset are the XOR of its leaf masks.
//
// Key identity making the inner loop O(1): with la = log2 size(sub),
// lb = log2 size(rest), lm = log2 size(sub XOR rest) all precomputed per
// mask, the contraction's op count (product of union dims) is
//   2^((la + lb + lm) / 2)
// because union = xor + shared, and shared contributes (la+lb-lm)/2.
//
// Exposed via ctypes from tnc_tpu/partitioning/native_binding.py; built
// together with partitioner.cpp into one shared library.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Sum of logdims over the set bits of a multi-word mask, via per-byte
// lookup tables built once per call.
struct ByteTables {
    // tables[byte_position][byte_value]
    std::vector<double> flat;  // (nwords*8) * 256
    int nwords;

    ByteTables(int nlegs, int nwords_, const double* leg_logdims)
        : flat(static_cast<size_t>(nwords_) * 8 * 256, 0.0), nwords(nwords_) {
        for (int pos = 0; pos < nwords * 8; ++pos) {
            double* table = &flat[static_cast<size_t>(pos) * 256];
            for (int value = 1; value < 256; ++value) {
                int low = value & (value - 1);
                int bit = __builtin_ctz(value);
                int leg = pos * 8 + bit;
                table[value] =
                    table[low] + (leg < nlegs ? leg_logdims[leg] : 0.0);
            }
        }
    }

    double logsize(const uint64_t* mask) const {
        double total = 0.0;
        for (int w = 0; w < nwords; ++w) {
            uint64_t word = mask[w];
            const double* base = &flat[static_cast<size_t>(w) * 8 * 256];
            for (int b = 0; b < 8 && word; ++b) {
                total += base[static_cast<size_t>(b) * 256 + (word & 0xff)];
                word >>= 8;
            }
        }
        return total;
    }
};

}  // namespace

extern "C" {

// Returns 0 on success, nonzero on invalid input. minimize: 0 = flops
// (sum of op counts), 1 = size (max intermediate element count).
// logsize_cap: if >= 0, any non-root intermediate with log2(size) >
// logsize_cap is forbidden (used by slice-aware reconfiguration);
// returns 1 if no ordering satisfies the cap.
// n is capped at 16: the subset DP is Theta(3^n) with no interruption
// point, so n=17..20 could stall a caller minutes past its time budget
// in a single uninterruptible solve (3^20 ~ 3.5e9 iterations).
int tnc_optimal_order(int n, int nlegs, const uint64_t* leaf_masks,
                      const double* leg_logdims, int minimize,
                      double logsize_cap, double* out_cost, int* out_pairs) {
    if (n < 2 || n > 16 || nlegs < 0) return 2;
    const int nwords = (nlegs + 63) / 64;
    if (nwords == 0) return 2;
    const uint32_t full = (n == 32) ? 0xffffffffu : ((1u << n) - 1);
    const size_t nmasks = static_cast<size_t>(full) + 1;

    ByteTables tables(nlegs, nwords, leg_logdims);

    // legs_of[mask] = XOR of member leaf masks; logsize[mask] alongside.
    std::vector<uint64_t> legs_of(nmasks * nwords, 0);
    std::vector<double> logsize(nmasks, 0.0);
    for (uint32_t mask = 1; mask <= full; ++mask) {
        uint32_t low = mask & (-mask);
        int leaf = __builtin_ctz(mask);
        const uint64_t* prev = &legs_of[static_cast<size_t>(mask ^ low) * nwords];
        const uint64_t* leaf_mask = &leaf_masks[static_cast<size_t>(leaf) * nwords];
        uint64_t* cur = &legs_of[static_cast<size_t>(mask) * nwords];
        for (int w = 0; w < nwords; ++w) cur[w] = prev[w] ^ leaf_mask[w];
        logsize[mask] = tables.logsize(cur);
    }

    const double inf = HUGE_VAL;
    std::vector<double> best(nmasks, inf);
    std::vector<uint32_t> split(nmasks, 0);
    for (int i = 0; i < n; ++i) best[1u << i] = 0.0;

    // Masks grouped by popcount so smaller subproblems are ready first.
    std::vector<std::vector<uint32_t>> by_count(n + 1);
    for (uint32_t mask = 1; mask <= full; ++mask)
        by_count[__builtin_popcount(mask)].push_back(mask);

    const bool by_size = minimize == 1;
    for (int count = 2; count <= n; ++count) {
        for (uint32_t mask : by_count[count]) {
            if (logsize_cap >= 0.0 && mask != full &&
                logsize[mask] > logsize_cap) {
                continue;  // intermediate too large under the cap
            }
            const uint32_t lowest = mask & (-mask);
            const double lm = logsize[mask];
            double best_cost = inf;
            uint32_t best_split = 0;
            // Enumerate submasks containing the lowest bit (canonical side).
            for (uint32_t sub = (mask - 1) & mask; sub; sub = (sub - 1) & mask) {
                if (!(sub & lowest)) continue;
                const uint32_t hi = mask ^ sub;
                const double c_lo = best[sub];
                const double c_hi = best[hi];
                if (c_lo == inf || c_hi == inf) continue;
                double cost;
                if (by_size) {
                    double out = exp2(lm);
                    cost = c_lo > c_hi ? c_lo : c_hi;
                    if (out > cost) cost = out;
                } else {
                    cost = c_lo + c_hi +
                           exp2(0.5 * (logsize[sub] + logsize[hi] + lm));
                }
                if (cost < best_cost) {
                    best_cost = cost;
                    best_split = sub;
                }
            }
            best[mask] = best_cost;
            split[mask] = best_split;
        }
    }
    if (best[full] == inf) return 1;

    // Reconstruct local SSA pairs (post-order, children before parents).
    int next_local = n;
    int out_idx = 0;
    // Iterative post-order: stack of (mask, stage).
    std::vector<std::pair<uint32_t, int>> stack;
    std::vector<int> node_of(nmasks, -1);
    stack.push_back({full, 0});
    while (!stack.empty()) {
        auto [mask, stage] = stack.back();
        stack.pop_back();
        if (__builtin_popcount(mask) == 1) {
            node_of[mask] = __builtin_ctz(mask);
            continue;
        }
        if (stage == 0) {
            stack.push_back({mask, 1});
            stack.push_back({split[mask], 0});
            stack.push_back({mask ^ split[mask], 0});
        } else {
            uint32_t lo = split[mask];
            out_pairs[out_idx * 2] = node_of[lo];
            out_pairs[out_idx * 2 + 1] = node_of[mask ^ lo];
            node_of[mask] = next_local++;
            ++out_idx;
        }
    }
    *out_cost = best[full];
    return 0;
}

}  // extern "C"
