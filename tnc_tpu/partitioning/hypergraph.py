"""Hypergraph representation of a tensor network.

The reference hands this job to KaHyPar (C++), building a hypergraph with
tensors as vertices and legs as hyperedges, edge weight
``1e5 * log2(bond_dim)`` — log because KaHyPar minimizes weight *sums*
while cut cost is a *product* of bond dims
(``tnc/src/tensornetwork/partitioning.rs:19,66-68``). This module is the
native replacement's data model; the partitioner itself lives in
``bisect.py``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Sequence

from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


@dataclass
class Hypergraph:
    """Vertices 0..n-1 with weights; hyperedges as pin lists with weights."""

    num_vertices: int
    vertex_weights: list[float]
    edge_pins: list[list[int]]  # per edge: vertices it connects
    edge_weights: list[float]
    vertex_edges: list[list[int]] = field(default_factory=list)  # incidence

    def __post_init__(self) -> None:
        if not self.vertex_edges:
            self.vertex_edges = [[] for _ in range(self.num_vertices)]
            for e, pins in enumerate(self.edge_pins):
                for v in pins:
                    self.vertex_edges[v].append(e)

    def total_vertex_weight(self) -> float:
        return sum(self.vertex_weights)

    def cut_weight(self, partition: Sequence[int]) -> float:
        """Total weight of hyperedges spanning more than one block."""
        cut = 0.0
        for pins, w in zip(self.edge_pins, self.edge_weights):
            first = partition[pins[0]]
            if any(partition[v] != first for v in pins[1:]):
                cut += w
        return cut

    def km1_weight(self, partition: Sequence[int]) -> float:
        """Connectivity metric ``sum_e w_e * (lambda_e - 1)`` where
        ``lambda_e`` counts the blocks edge ``e`` touches — KaHyPar's
        km1 objective, the second preset the reference embeds
        (``tnc/src/tensornetwork/partition_config.rs:12-36``). Equals
        :meth:`cut_weight` for 2 blocks; diverges for k > 2, where it
        additionally penalizes edges *scattered across many* blocks
        (each extra block touched is one more fan-in transfer of that
        bond in the distributed runtime)."""
        total = 0.0
        for pins, w in zip(self.edge_pins, self.edge_weights):
            lam = len({partition[v] for v in pins})
            if lam > 1:
                total += w * (lam - 1)
        return total


def hypergraph_from_tensors(
    tensors: Sequence[LeafTensor | CompositeTensor],
    weight_scale: float = 1e5,
    unit_vertex_weights: bool = True,
) -> Hypergraph:
    """Build the partitioning hypergraph of a network: one vertex per
    (externalized) tensor, one hyperedge per shared leg, edge weight
    ``weight_scale * log2(bond_dim)`` (``partitioning.rs:40-68``).

    Legs appearing in a single tensor (open legs) produce no hyperedge.
    With ``unit_vertex_weights`` False, vertex weight = log2(tensor size),
    so balance constrains memory rather than tensor count.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> hg = hypergraph_from_tensors([LeafTensor([0, 1], [2, 2]),
    ...     LeafTensor([1, 2], [2, 2]), LeafTensor([2, 3], [2, 2])])
    >>> hg.num_vertices, len(hg.edge_pins)   # legs 1 and 2 are shared
    (3, 2)
    """
    leaves = [
        t.external_tensor() if isinstance(t, CompositeTensor) else t for t in tensors
    ]
    leg_pins: dict[int, list[int]] = {}
    leg_dims: dict[int, int] = {}
    for v, leaf in enumerate(leaves):
        for leg, dim in leaf.edges():
            leg_pins.setdefault(leg, []).append(v)
            leg_dims[leg] = dim

    edge_pins = []
    edge_weights = []
    for leg in sorted(leg_pins):
        pins = leg_pins[leg]
        if len(pins) < 2:
            continue
        edge_pins.append(pins)
        edge_weights.append(weight_scale * math.log2(max(2, leg_dims[leg])))

    if unit_vertex_weights:
        vertex_weights = [1.0] * len(leaves)
    else:
        vertex_weights = [max(1.0, math.log2(max(2.0, t.size()))) for t in leaves]

    return Hypergraph(len(leaves), vertex_weights, edge_pins, edge_weights)
