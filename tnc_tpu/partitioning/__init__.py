from tnc_tpu.partitioning.hypergraph import Hypergraph  # noqa: F401
from tnc_tpu.partitioning.bisect import bisect, partition_kway  # noqa: F401
