"""QAOA circuit generation.

The survey's benchmark config #4 is a 30-qubit QAOA Pauli-string
expectation value (BASELINE.md, driver config table; the reference feeds
such circuits in as QASM through its benchmark crate). This builder
produces the standard QAOA ansatz for MaxCut on a given coupling graph:

    |+…+>  then p rounds of  [ exp(-i γ Z_u Z_v) on every edge,
                               exp(-i β X_q) on every qubit ]

with ZZ interactions compiled to the cx–rz–cx pattern. The circuit
closes as a ⟨ψ|Z…Z|ψ⟩ expectation network via
``Circuit.into_expectation_value_network`` (reference finalizer:
``builders/circuit_builder.rs:304-326``).
"""

from __future__ import annotations

import numpy as np

from tnc_tpu.builders.circuit_builder import Circuit
from tnc_tpu.builders.connectivity import Connectivity, ConnectivityLayout
from tnc_tpu.tensornetwork.tensordata import TensorData


def qaoa_circuit(
    qubits: int,
    rounds: int,
    rng: np.random.Generator,
    layout: ConnectivityLayout = ConnectivityLayout.LINE,
) -> Circuit:
    """QAOA MaxCut ansatz with ``rounds`` (γ, β) layers of random angles
    on the ``layout`` coupling graph (default: a line of ``qubits``).

    >>> import numpy as np
    >>> c = qaoa_circuit(4, 2, np.random.default_rng(0))
    >>> tn = c.into_expectation_value_network()
    >>> tn.external_tensor().legs  # <psi|Z...Z|psi> closes every leg
    []
    """
    graph = Connectivity.new(layout, qubits)
    edges = [(u, v) for (u, v) in graph.connectivity if u < qubits and v < qubits]

    circuit = Circuit()
    reg = circuit.allocate_register(qubits)

    for q in range(qubits):
        circuit.append_gate(TensorData.gate("h"), [reg.qubit(q)])

    for _ in range(rounds):
        gamma = float(rng.uniform(0, 2 * np.pi))
        beta = float(rng.uniform(0, np.pi))
        for u, v in edges:
            # exp(-i gamma Z_u Z_v) = cx(u,v) rz(2*gamma, v) cx(u,v)
            circuit.append_gate(
                TensorData.gate("cx"), [reg.qubit(u), reg.qubit(v)]
            )
            circuit.append_gate(
                TensorData.gate("rz", [2.0 * gamma]), [reg.qubit(v)]
            )
            circuit.append_gate(
                TensorData.gate("cx"), [reg.qubit(u), reg.qubit(v)]
            )
        for q in range(qubits):
            circuit.append_gate(
                TensorData.gate("rx", [2.0 * beta]), [reg.qubit(q)]
            )
    return circuit
