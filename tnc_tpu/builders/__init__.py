from tnc_tpu.builders.circuit_builder import (  # noqa: F401
    Circuit,
    Permutor,
    QuantumRegister,
    Qubit,
)
from tnc_tpu.builders.connectivity import (  # noqa: F401
    Connectivity,
    ConnectivityLayout,
)
from tnc_tpu.builders.peps import peps  # noqa: F401
from tnc_tpu.builders.random_circuit import (  # noqa: F401
    random_circuit,
    random_circuit_with_observable,
    random_circuit_with_set_observable,
)
from tnc_tpu.builders.sycamore_circuit import sycamore_circuit  # noqa: F401
from tnc_tpu.builders.tensorgeneration import (  # noqa: F401
    random_sparse_tensor_data,
    random_sparse_tensor_data_with_rng,
)
