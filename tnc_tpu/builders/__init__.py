from tnc_tpu.builders.circuit_builder import (  # noqa: F401
    Circuit,
    Permutor,
    QuantumRegister,
    Qubit,
)
