"""PEPS sandwich network generation.

Equivalent of ``tnc/src/builders/peps.rs:446-460``: builds the 2-D tensor
network of ⟨PEPS|PEPO^layers|PEPS⟩ on a ``length × depth`` grid — a bottom
PEPS layer, ``layers`` PEPO layers, and a top (bra) PEPS layer. Virtual
bonds (dimension ``virtual_dim``) connect lattice neighbours within a
layer; physical bonds (dimension ``physical_dim``) connect consecutive
layers vertically. The network is closed (no open legs). Tensors are
metadata-only, as in the reference — the structure is a planning/benchmark
workload.

The reference writes out corner/edge/bulk leg arithmetic explicitly
(~900 lines); here a single edge allocator handles all cases.
"""

from __future__ import annotations

from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


def peps(
    length: int,
    depth: int,
    physical_dim: int,
    virtual_dim: int,
    layers: int,
) -> CompositeTensor:
    """Build the closed PEPS/PEPO sandwich network.

    Total tensors: ``(layers + 2) * length * depth``.

    >>> tn = peps(3, 3, 2, 3, 1)
    >>> len(tn.tensors)            # (1 + 2) * 3 * 3
    27
    >>> tn.external_tensor().legs  # closed sandwich: no open legs
    []
    """
    if length < 2:
        raise ValueError("PEPS should have length greater than 1")
    if depth < 2:
        raise ValueError("PEPS should have depth greater than 1")

    next_edge = 0

    def new_edge() -> int:
        nonlocal next_edge
        edge = next_edge
        next_edge += 1
        return edge

    n_layers = layers + 2  # bottom PEPS + PEPOs + top PEPS
    tensors: list[LeafTensor] = []

    # Virtual bonds within each layer: right[(k, r, c)] connects (r, c)-(r, c+1),
    # down[(k, r, c)] connects (r, c)-(r+1, c).
    right: dict[tuple[int, int, int], int] = {}
    down: dict[tuple[int, int, int], int] = {}
    for k in range(n_layers):
        for r in range(depth):
            for c in range(length):
                if c + 1 < length:
                    right[(k, r, c)] = new_edge()
                if r + 1 < depth:
                    down[(k, r, c)] = new_edge()

    # Physical bonds between consecutive layers.
    vertical: dict[tuple[int, int, int], int] = {}
    for k in range(n_layers - 1):
        for r in range(depth):
            for c in range(length):
                vertical[(k, r, c)] = new_edge()

    for k in range(n_layers):
        for r in range(depth):
            for c in range(length):
                legs: list[int] = []
                dims: list[int] = []
                # Physical legs: down to layer below, up to layer above.
                if k > 0:
                    legs.append(vertical[(k - 1, r, c)])
                    dims.append(physical_dim)
                if k + 1 < n_layers:
                    legs.append(vertical[(k, r, c)])
                    dims.append(physical_dim)
                # Virtual bonds: left, right, up, down within the layer.
                if c > 0:
                    legs.append(right[(k, r, c - 1)])
                    dims.append(virtual_dim)
                if c + 1 < length:
                    legs.append(right[(k, r, c)])
                    dims.append(virtual_dim)
                if r > 0:
                    legs.append(down[(k, r - 1, c)])
                    dims.append(virtual_dim)
                if r + 1 < depth:
                    legs.append(down[(k, r, c)])
                    dims.append(virtual_dim)
                tensors.append(LeafTensor(legs, dims))

    return CompositeTensor(tensors)
