"""Sycamore-style random circuit generation.

Mirror of ``tnc/src/builders/sycamore_circuit.rs:23-74`` (circuit scheme
from arXiv:1910.11333): ``depth`` rounds, each a layer of random
single-qubit gates from {sx, sy, sz} followed by a layer of
fsim(pi/2, pi/6) two-qubit gates on the round's activation pattern, cycling
[a, b, c, d, c, d, a, b]; a final single-qubit layer closes the circuit.
Pattern qubit labels are 1-based; pairs outside the qubit count are
skipped, as in the reference.
"""

from __future__ import annotations

import math
from itertools import cycle

import numpy as np

from tnc_tpu.builders.circuit_builder import Circuit
from tnc_tpu.builders.connectivity import (
    sycamore_a,
    sycamore_b,
    sycamore_c,
    sycamore_d,
)
from tnc_tpu.tensornetwork.tensordata import TensorData

_SINGLE_QUBIT_GATES = ("sx", "sy", "sz")


def sycamore_circuit(
    qubits: int, depth: int, rng: np.random.Generator | None = None
) -> Circuit:
    """Build a Sycamore-scheme circuit on ``qubits`` qubits with ``depth``
    rounds. ``qubits`` is capped at 53 (the original device size).

    >>> import numpy as np
    >>> tn, _ = sycamore_circuit(12, 4, np.random.default_rng(1)
    ...     ).into_amplitude_network("0" * 12)
    >>> len(tn.tensors) > 12 and tn.external_tensor().legs == []
    True
    >>> sycamore_circuit(54, 1)
    Traceback (most recent call last):
        ...
    ValueError: Only circuits up to the original 53-qubit Sycamore device are supported
    """
    if qubits > 53:
        raise ValueError(
            "Only circuits up to the original 53-qubit Sycamore device are supported"
        )
    if rng is None:
        rng = np.random.default_rng()

    rounds = cycle(
        [
            sycamore_a, sycamore_b, sycamore_c, sycamore_d,
            sycamore_c, sycamore_d, sycamore_a, sycamore_b,
        ]
    )
    two_qubit_gate = TensorData.gate("fsim", (math.pi / 2.0, math.pi / 6.0))

    circuit = Circuit()
    qreg = circuit.allocate_register(qubits)

    for round_idx in range(depth + 1):
        for i in range(qubits):
            name = _SINGLE_QUBIT_GATES[int(rng.integers(0, 3))]
            circuit.append_gate(TensorData.gate(name), [qreg.qubit(i)])
        if round_idx < depth:
            layer = next(rounds)()
            for i, j in layer:
                if i > qubits or j > qubits:
                    continue
                circuit.append_gate(
                    two_qubit_gate, [qreg.qubit(i - 1), qreg.qubit(j - 1)]
                )
    return circuit
