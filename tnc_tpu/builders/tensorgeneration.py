"""Random sparse tensor data generation
(mirror of ``tnc/src/builders/tensorgeneration.rs``).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tnc_tpu.tensornetwork.tensordata import TensorData


def random_sparse_tensor_data_with_rng(
    dims: Sequence[int],
    sparsity: float | None,
    rng: np.random.Generator,
) -> TensorData:
    """Fill random complex entries at random locations until the fill
    fraction reaches ``sparsity`` (default 0.5)
    (``tensorgeneration.rs:19-55``).

    >>> import numpy as np
    >>> data = random_sparse_tensor_data_with_rng(
    ...     [2, 2], 0.5, np.random.default_rng(0))
    >>> arr = data.into_data()
    >>> arr.shape, int((arr != 0).sum())
    ((2, 2), 2)
    """
    if sparsity is None:
        sparsity = 0.5
    if not 0.0 <= sparsity <= 1.0:
        raise ValueError("sparsity must be in [0, 1]")

    size = 1
    for d in dims:
        size *= d
    tensor = np.zeros(tuple(dims), dtype=np.complex128)
    nnz = 0
    while size and nnz / size < sparsity:
        loc = tuple(int(rng.integers(0, d)) for d in dims)
        if tensor[loc] != 0:
            continue
        tensor[loc] = complex(rng.random(), rng.random())
        nnz += 1
    return TensorData.matrix(tensor)


def random_sparse_tensor_data(
    dims: Sequence[int], sparsity: float | None = None
) -> TensorData:
    return random_sparse_tensor_data_with_rng(dims, sparsity, np.random.default_rng())
