"""Quantum-circuit → tensor-network builder.

Mirror of ``tnc/src/builders/circuit_builder.rs``:

- ``allocate_register(n)`` pushes |0⟩ kets, one edge each
  (``circuit_builder.rs:176-194``).
- ``append_gate(data, qubits)`` creates a tensor whose legs are the *new*
  output edges first, then the old input edges (``edges = new ++ old``,
  ``circuit_builder.rs:197-220``) — matching the gate storage layout
  ``(out…, in…)``.
- Three finalizers: ``into_amplitude_network(bitstring)`` (``0``/``1``/``*``
  wildcards → open legs), ``into_statevector_network()`` (all wildcards),
  and ``into_expectation_value_network()`` (circuit + adjoint mirror +
  Z-observable layer computing ⟨ψ|Z…Z|ψ⟩) (``circuit_builder.rs:235-326``).
- A :class:`Permutor` restores natural qubit order after contraction,
  since the contraction can emit the open legs in any order
  (``circuit_builder.rs:77-122``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, Sequence

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor, EdgeIndex, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


def normalize_bitstring(
    bitstring: str | Iterable, num_qubits: int | None = None
) -> str:
    """Canonicalize a bitstring spec to a ``str`` of ``0``/``1``/``*``.

    Accepts a plain string or an iterable of per-qubit states: the
    characters ``"0"``/``"1"``/``"*"``, the ints ``0``/``1``, or
    ``None`` (= open leg, like ``"*"``). Errors name the offending
    state *and its position*, so a 53-character Sycamore bitstring with
    one typo is debuggable.

    >>> normalize_bitstring([0, 1, None, "1"])
    '01*1'
    >>> normalize_bitstring("01x1")
    Traceback (most recent call last):
        ...
    ValueError: invalid bitstring character 'x' at position 2 (only '0', '1' and '*' are allowed)
    """
    chars: list[str] = []
    for pos, state in enumerate(bitstring):
        if isinstance(state, str) and state in ("0", "1", "*"):
            chars.append(state)
        elif state is None:
            chars.append("*")
        elif (
            isinstance(state, (int, np.integer))
            and not isinstance(state, bool)
            and state in (0, 1)
        ):
            chars.append(str(int(state)))
        else:
            what = (
                f"character {state!r}"
                if isinstance(state, str)
                else f"state {state!r}"
            )
            raise ValueError(
                f"invalid bitstring {what} at position {pos} "
                "(only '0', '1' and '*' are allowed)"
            )
    if num_qubits is not None and len(chars) != num_qubits:
        raise ValueError(
            f"bitstring length {len(chars)} != qubit count {num_qubits}"
        )
    return "".join(chars)


class Qubit:
    """A single qubit handle (global index into the circuit)."""

    __slots__ = ("index",)

    def __init__(self, index: int) -> None:
        self.index = index


class QuantumRegister:
    """An array of qubits (``circuit_builder.rs:21-67``)."""

    def __init__(self, base: int, size: int) -> None:
        self.base = base
        self.size = size

    def qubit(self, index: int) -> Qubit:
        if not 0 <= index < self.size:
            raise IndexError(f"qubit index {index} out of range for register of size {self.size}")
        return Qubit(self.base + index)

    def qubits(self) -> Iterator[Qubit]:
        return (Qubit(i) for i in range(self.base, self.base + self.size))

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, index: int) -> Qubit:
        return self.qubit(index)


class Permutor:
    """Transposes the final tensor to the target (natural) leg order
    (``circuit_builder.rs:77-122``).

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> import numpy as np
    >>> t = LeafTensor([5, 3], [2, 4],
    ...     TensorData.matrix(np.arange(8.0).reshape(2, 4)))
    >>> Permutor([3, 5]).apply(t).bond_dims
    [4, 2]
    >>> Permutor([]).is_identity()
    True
    """

    def __init__(self, target_leg_order: Sequence[EdgeIndex]) -> None:
        self.target_leg_order = list(target_leg_order)

    def is_identity(self) -> bool:
        return not self.target_leg_order

    def apply(self, tensor: LeafTensor) -> LeafTensor:
        if self.is_identity():
            return tensor
        if sorted(tensor.legs) != sorted(self.target_leg_order):
            raise ValueError(
                f"tensor legs {tensor.legs} are not a permutation of target "
                f"{self.target_leg_order}"
            )
        # axes[k] = position in `tensor.legs` of the k-th target leg
        pos = {leg: i for i, leg in enumerate(tensor.legs)}
        axes = [pos[leg] for leg in self.target_leg_order]
        data = np.transpose(tensor.data.into_data(), axes)
        bond_dims = [tensor.bond_dims[a] for a in axes]
        return LeafTensor(self.target_leg_order, bond_dims, TensorData.matrix(data))


# The canonical computational-basis one-hot values. This table is THE
# single definition of the ⟨0|/⟨1| (equivalently |0⟩/|1⟩ — they are
# real) vectors in the codebase: the builder's ket/bra leaves, the
# serving layer's rebind bras (:mod:`tnc_tpu.serve.rebind`), and the
# sweep layer's stacked kets (:mod:`tnc_tpu.tensornetwork.sweep`) all
# read it, so a future dtype/layout change cannot skew them apart.
BASIS_STATES: dict[str, np.ndarray] = {
    "0": np.array([1.0 + 0.0j, 0.0 + 0.0j]),
    "1": np.array([0.0 + 0.0j, 1.0 + 0.0j]),
}

# Single-qubit Pauli matrices in the gate storage layout ``[out, in]``
# — the observable alphabet of expectation-value networks
# (:meth:`Circuit.into_expectation_value_network`,
# :mod:`tnc_tpu.queries.expectation`).
PAULI_MATRICES: dict[str, np.ndarray] = {
    "i": np.eye(2, dtype=np.complex128),
    "x": np.array([[0, 1], [1, 0]], dtype=np.complex128),
    "y": np.array([[0, -1j], [1j, 0]], dtype=np.complex128),
    "z": np.array([[1, 0], [0, -1]], dtype=np.complex128),
}


def observable_leaf_data(matrix: np.ndarray) -> TensorData:
    """Leaf data for an observable ``O`` inserted between a sandwich
    network's ket and adjoint layers (legs ``[edge, edge + offset]``).

    The contraction computes ``sum_{a,b} psi_a T[a, b] conj(psi)_b``
    for leaf data ``T`` — that is ⟨ψ|Tᵀ|ψ⟩ — so the leaf stores the
    TRANSPOSE of the operator to make the network value ⟨ψ|O|ψ⟩.
    (Symmetric observables — i, x, z and the reference's Z layer — are
    unchanged by this; y is where the convention matters.)
    """
    return TensorData.matrix(
        np.asarray(matrix, dtype=np.complex128).T.copy()
    )


def _ket0() -> TensorData:
    return TensorData.matrix(BASIS_STATES["0"].copy())


def _ket1() -> TensorData:
    return TensorData.matrix(BASIS_STATES["1"].copy())


class Circuit:
    """Tensor-network circuit builder (``circuit_builder.rs:127-134``)."""

    def __init__(self) -> None:
        self.open_edges: list[EdgeIndex] = []
        self.next_edge: int = 0
        self.tensor_network = CompositeTensor()
        self._finalized = False

    def _finalize(self) -> None:
        """Finalizers consume the builder (the reference takes ``self`` by
        value); a second finalizer call would corrupt the network.
        """
        if self._finalized:
            raise RuntimeError(
                "Circuit was already converted to a network; build a new Circuit"
            )
        self._finalized = True

    def _new_edge(self) -> EdgeIndex:
        edge = self.next_edge
        self.next_edge += 1
        return edge

    def num_qubits(self) -> int:
        return len(self.open_edges)

    def copy(self) -> "Circuit":
        """An independent, un-finalized copy of this circuit.

        Finalizers consume a circuit; query layers that need several
        networks from one logical circuit — e.g. the chain-rule sampler
        builds one marginal network per prefix length
        (:mod:`tnc_tpu.queries.sampling`) — copy first and finalize the
        copies. Leaf *data* is shared (finalizers only append tensors,
        never mutate existing ones); the tensor list and edge
        bookkeeping are fresh.
        """
        if self._finalized:
            raise RuntimeError(
                "Circuit was already converted to a network; nothing to copy"
            )
        dup = Circuit()
        dup.open_edges = list(self.open_edges)
        dup.next_edge = self.next_edge
        dup.tensor_network = self.tensor_network.copy()
        return dup

    def allocate_register(self, size: int) -> QuantumRegister:
        """Allocate ``size`` qubits initialized to |0⟩."""
        if self._finalized:
            raise RuntimeError("Circuit was already converted to a network")
        base = self.num_qubits()
        for _ in range(size):
            edge = self._new_edge()
            self.open_edges.append(edge)
            ket = LeafTensor.from_const([edge], 2)
            ket.data = _ket0()
            self.tensor_network.push_tensor(ket)
        return QuantumRegister(base, size)

    def append_gate(self, gate: TensorData, qubits: Sequence[Qubit]) -> None:
        """Append a gate tensor acting on ``qubits``; legs = new ++ old."""
        if self._finalized:
            raise RuntimeError("Circuit was already converted to a network")
        indices = [q.index for q in qubits]
        if len(set(indices)) != len(indices):
            raise ValueError("Qubit arguments must be unique")

        old_edges = [self.open_edges[i] for i in indices]
        new_edges = [self.next_edge + k for k in range(len(indices))]
        self.next_edge += len(indices)
        for qubit_index, new_edge in zip(indices, new_edges):
            self.open_edges[qubit_index] = new_edge

        tensor = LeafTensor.from_const(new_edges + old_edges, 2)
        tensor.data = gate
        self.tensor_network.push_tensor(tensor)

    # -- finalizers --------------------------------------------------------

    def into_amplitude_network(
        self, bitstring: str | Iterable
    ) -> tuple[CompositeTensor, Permutor]:
        """Close the circuit with ⟨0|/⟨1| bras per the bitstring; ``*``
        leaves the leg open (statevector slice). Returns the network and a
        Permutor for the open legs in qubit order.

        ``bitstring`` may also be an iterable of per-qubit states
        (``0``/``1`` ints, ``"0"``/``"1"``/``"*"`` chars, or ``None``
        for an open leg — :func:`normalize_bitstring`).
        """
        bitstring = normalize_bitstring(bitstring, self.num_qubits())
        self._finalize()
        final_legs: list[EdgeIndex] = []
        for c, edge in zip(bitstring, self.open_edges):
            if c == "*":
                final_legs.append(edge)
                continue
            bra = LeafTensor.from_const([edge], 2)
            bra.data = _ket0() if c == "0" else _ket1()
            self.tensor_network.push_tensor(bra)
        return self.tensor_network, Permutor(final_legs)

    def into_amplitude_template(
        self, mask: str | Iterable | None = None
    ) -> "AmplitudeTemplate":
        """Close the circuit with *symbolic* bra placeholders — the
        serving finalizer (:mod:`tnc_tpu.serve`).

        ``mask`` says only which positions are *determined* (get a bra
        leaf, value bound later) vs *open* (``"*"``, statevector
        slice); any determined character (``0``/``1``) is a placeholder
        — the template's network structure, contraction path, and
        compiled program are bitstring-independent, and per-request bra
        values are rebound without replanning
        (:mod:`tnc_tpu.serve.rebind`). Placeholder bras materialize as
        ⟨0| so the template network stays directly executable.

        Returns an :class:`AmplitudeTemplate`; the bra leaves are the
        trailing ``len(determined)`` leaves of the network, in qubit
        order (the slot contract the rebind layer relies on).
        """
        if mask is None:
            mask = "0" * self.num_qubits()
        mask = normalize_bitstring(mask, self.num_qubits())
        network, permutor = self.into_amplitude_network(mask)
        determined = tuple(i for i, c in enumerate(mask) if c != "*")
        return AmplitudeTemplate(
            network=network,
            permutor=permutor,
            num_qubits=len(mask),
            determined=determined,
            mask="".join("*" if c == "*" else "?" for c in mask),
        )

    def into_statevector_network(self) -> tuple[CompositeTensor, Permutor]:
        return self.into_amplitude_network("*" * self.num_qubits())

    @staticmethod
    def _tensor_adjoint(tensor: LeafTensor, leg_offset: int) -> LeafTensor:
        """Adjoint with legs half-swapped and offset
        (``circuit_builder.rs:278-297``).
        """
        half = len(tensor.legs) // 2
        legs = [l + leg_offset for l in tensor.legs[half:] + tensor.legs[:half]]
        bond_dims = tensor.bond_dims[half:] + tensor.bond_dims[:half]
        return LeafTensor(legs, bond_dims, tensor.data.adjoint())

    def _mirror_adjoint(self) -> int:
        """Finalize and append the adjoint mirror of every circuit
        tensor; returns the leg ``offset`` such that qubit ``q``'s
        adjoint-layer open leg is ``self.open_edges[q] + offset``."""
        self._finalize()
        offset = self.next_edge
        adjoints = [
            self._tensor_adjoint(t, offset) for t in self.tensor_network.tensors
        ]
        self.tensor_network.push_tensors(adjoints)
        return offset

    def into_expectation_value_network(
        self, observables: str | None = None
    ) -> CompositeTensor:
        """⟨ψ|P₁⊗…⊗Pₙ|ψ⟩ network: circuit ++ adjoint mirror ++ an
        observable layer (``circuit_builder.rs:304-326``).

        ``observables``: one Pauli character per qubit (``i``/``x``/
        ``y``/``z``); default ``"z" * n`` — the reference's ⟨ψ|Z…Z|ψ⟩
        layer. ``i`` traces the qubit out (its contribution is the
        identity between the layers). The network contracts to the
        scalar expectation value (real for Hermitian observables, up to
        roundoff).
        """
        if observables is None:
            observables = "z" * self.num_qubits()
        observables = str(observables).lower()
        if len(observables) != self.num_qubits():
            raise ValueError(
                f"observable string length {len(observables)} != qubit "
                f"count {self.num_qubits()}"
            )
        for pos, c in enumerate(observables):
            if c not in PAULI_MATRICES:
                raise ValueError(
                    f"invalid observable {c!r} at position {pos} "
                    "(only 'i', 'x', 'y' and 'z' are allowed)"
                )
        offset = self._mirror_adjoint()
        for c, edge in zip(observables, self.open_edges):
            observable = LeafTensor.from_const([edge, edge + offset], 2)
            observable.data = observable_leaf_data(PAULI_MATRICES[c])
            self.tensor_network.push_tensor(observable)
        return self.tensor_network

    def into_sandwich_template(
        self, spec: str | Iterable
    ) -> "SandwichTemplate":
        """Close the circuit ++ adjoint mirror *sandwich* with one
        closure per qubit — the query-engine finalizer
        (:mod:`tnc_tpu.queries`). ``spec`` gives one character per
        qubit:

        - ``?`` — **determined**: placeholder ⟨b| bras on BOTH layers
          (the ket-layer bra and its adjoint-layer mirror), rebound
          per request like amplitude-template bras;
        - ``*`` — **marginalized**: the qubit's ket-layer leg is traced
          against its adjoint-layer mirror (an identity leaf), summing
          the born-rule probability over that qubit;
        - ``o`` — **open**: both legs stay open (the result carries a
          ``(2, 2)`` density block for the qubit — its diagonal is the
          pair of marginal probabilities);
        - ``p`` — **observable placeholder**: one rebindable 2×2
          operator leaf between the layers (identity until rebound;
          see :func:`observable_leaf_data` for the stored layout).

        The rebindable leaves are the TRAILING leaves of the network,
        in qubit order — for each ``?`` qubit the ket-layer bra then
        the adjoint-layer bra, one leaf per ``p`` qubit — the slot
        contract :func:`tnc_tpu.serve.rebind.bind_template` relies on.
        ``?`` and ``p`` cannot be mixed in one template (a template is
        either bra-rebindable or observable-rebindable).
        """
        spec = "".join(spec)
        if len(spec) != self.num_qubits():
            raise ValueError(
                f"sandwich spec length {len(spec)} != qubit count "
                f"{self.num_qubits()}"
            )
        for pos, c in enumerate(spec):
            if c not in "?*op":
                raise ValueError(
                    f"invalid sandwich spec character {c!r} at position "
                    f"{pos} (only '?', '*', 'o' and 'p' are allowed)"
                )
        if "?" in spec and "p" in spec:
            raise ValueError(
                "a sandwich template is either bra-rebindable ('?') or "
                "observable-rebindable ('p'), not both"
            )
        offset = self._mirror_adjoint()
        open_legs: list[EdgeIndex] = []
        determined: list[int] = []
        rebind: list[LeafTensor] = []
        for q, (c, edge) in enumerate(zip(spec, self.open_edges)):
            if c == "*":
                trace = LeafTensor.from_const([edge, edge + offset], 2)
                trace.data = observable_leaf_data(PAULI_MATRICES["i"])
                self.tensor_network.push_tensor(trace)
            elif c == "o":
                open_legs.extend((edge, edge + offset))
            elif c == "?":
                for leg in (edge, edge + offset):
                    bra = LeafTensor.from_const([leg], 2)
                    bra.data = _ket0()
                    rebind.append(bra)
                determined.extend((q, q))
            else:  # 'p'
                op = LeafTensor.from_const([edge, edge + offset], 2)
                op.data = observable_leaf_data(PAULI_MATRICES["i"])
                rebind.append(op)
                determined.append(q)
        self.tensor_network.push_tensors(rebind)
        return SandwichTemplate(
            network=self.tensor_network,
            permutor=Permutor(open_legs),
            num_qubits=len(spec),
            determined=tuple(determined),
            spec=spec,
        )


@dataclass(frozen=True)
class AmplitudeTemplate:
    """A circuit closed with symbolic bras (``into_amplitude_template``).

    ``network`` is a normal amplitude network whose trailing
    ``len(determined)`` leaves are placeholder bras (one per determined
    qubit, in qubit order); ``determined`` are the qubit positions that
    carry a bra, the rest are open legs. A request bitstring supplies
    one ``0``/``1`` per determined position; the open positions stay
    ``*`` in every request.
    """

    network: CompositeTensor
    permutor: Permutor
    num_qubits: int
    determined: tuple[int, ...]
    mask: str  # '?' per determined position, '*' per open one

    @property
    def open_positions(self) -> frozenset[int]:
        """Positions with no bra (computed once per template —
        request validation runs per serving request)."""
        cached = getattr(self, "_open_positions", None)
        if cached is None:
            cached = frozenset(range(self.num_qubits)) - frozenset(
                self.determined
            )
            object.__setattr__(self, "_open_positions", cached)
        return cached

    def normalize_request(self, bitstring: str | Iterable) -> str:
        """Validate a request against the template and return it as a
        canonical full-length ``str``. One-shot iterables (generators)
        are consumed exactly once here — callers that validate early
        must carry THIS string forward, not the original object."""
        bits = normalize_bitstring(bitstring, self.num_qubits)
        open_set = self.open_positions
        for pos, c in enumerate(bits):
            if pos in open_set and c != "*":
                raise ValueError(
                    f"position {pos} is an open leg in this template; "
                    f"request must use '*' there, got {c!r}"
                )
            if pos not in open_set and c == "*":
                raise ValueError(
                    f"position {pos} is determined in this template; "
                    "request must supply '0' or '1' there"
                )
        return bits

    def request_bits(self, bitstring: str | Iterable) -> str:
        """The determined positions' bits of a validated request (a
        ``len(self.determined)``-char ``0``/``1`` string, qubit order)."""
        bits = self.normalize_request(bitstring)
        return "".join(bits[p] for p in self.determined)


@dataclass(frozen=True)
class SandwichTemplate:
    """A circuit ++ adjoint sandwich closed with rebindable leaves
    (:meth:`Circuit.into_sandwich_template`).

    Shares the :class:`AmplitudeTemplate` slot contract — the trailing
    ``len(determined)`` leaves of ``network`` are the rebindable slots
    — so :func:`tnc_tpu.serve.rebind.bind_template` plans, caches and
    compiles it unchanged. ``determined[i]`` is the qubit index slot
    ``i`` serves: a ``?`` qubit contributes TWO consecutive slots (its
    ket-layer bra, then the adjoint-layer mirror), a ``p`` qubit one
    observable slot.
    """

    network: CompositeTensor
    permutor: Permutor
    num_qubits: int
    determined: tuple[int, ...]  # one qubit index per rebindable slot
    spec: str  # per-qubit '?', '*', 'o' or 'p'

    @property
    def bra_qubits(self) -> tuple[int, ...]:
        """The determined ('?') qubit positions, in qubit order."""
        return tuple(q for q, c in enumerate(self.spec) if c == "?")

    @property
    def observable_qubits(self) -> tuple[int, ...]:
        """The observable-placeholder ('p') positions, in qubit order."""
        return tuple(q for q, c in enumerate(self.spec) if c == "p")

    def request_bits(self, bits: str | Iterable) -> str:
        """Per-slot bra bits for a request that fixes each determined
        qubit: one ``0``/``1`` per ``?`` qubit, in qubit order, doubled
        per slot (both layers carry the same one-hot value — the bras
        are real). The :class:`~tnc_tpu.serve.rebind.BoundProgram`
        dispatch contract.

        >>> from tnc_tpu.tensornetwork.tensordata import TensorData
        >>> c = Circuit(); _ = c.allocate_register(3)
        >>> c.into_sandwich_template("??*").request_bits("01")
        '0011'
        """
        bits = normalize_bitstring(bits, len(self.bra_qubits))
        for pos, c in enumerate(bits):
            if c == "*":
                raise ValueError(
                    f"sandwich request bit {pos} must be '0' or '1' "
                    "(wildcards are fixed by the template spec)"
                )
        return "".join(c + c for c in bits)
