"""Quantum-circuit → tensor-network builder.

Mirror of ``tnc/src/builders/circuit_builder.rs``:

- ``allocate_register(n)`` pushes |0⟩ kets, one edge each
  (``circuit_builder.rs:176-194``).
- ``append_gate(data, qubits)`` creates a tensor whose legs are the *new*
  output edges first, then the old input edges (``edges = new ++ old``,
  ``circuit_builder.rs:197-220``) — matching the gate storage layout
  ``(out…, in…)``.
- Three finalizers: ``into_amplitude_network(bitstring)`` (``0``/``1``/``*``
  wildcards → open legs), ``into_statevector_network()`` (all wildcards),
  and ``into_expectation_value_network()`` (circuit + adjoint mirror +
  Z-observable layer computing ⟨ψ|Z…Z|ψ⟩) (``circuit_builder.rs:235-326``).
- A :class:`Permutor` restores natural qubit order after contraction,
  since the contraction can emit the open legs in any order
  (``circuit_builder.rs:77-122``).
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor, EdgeIndex, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


class Qubit:
    """A single qubit handle (global index into the circuit)."""

    __slots__ = ("index",)

    def __init__(self, index: int) -> None:
        self.index = index


class QuantumRegister:
    """An array of qubits (``circuit_builder.rs:21-67``)."""

    def __init__(self, base: int, size: int) -> None:
        self.base = base
        self.size = size

    def qubit(self, index: int) -> Qubit:
        if not 0 <= index < self.size:
            raise IndexError(f"qubit index {index} out of range for register of size {self.size}")
        return Qubit(self.base + index)

    def qubits(self) -> Iterator[Qubit]:
        return (Qubit(i) for i in range(self.base, self.base + self.size))

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, index: int) -> Qubit:
        return self.qubit(index)


class Permutor:
    """Transposes the final tensor to the target (natural) leg order
    (``circuit_builder.rs:77-122``).

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> import numpy as np
    >>> t = LeafTensor([5, 3], [2, 4],
    ...     TensorData.matrix(np.arange(8.0).reshape(2, 4)))
    >>> Permutor([3, 5]).apply(t).bond_dims
    [4, 2]
    >>> Permutor([]).is_identity()
    True
    """

    def __init__(self, target_leg_order: Sequence[EdgeIndex]) -> None:
        self.target_leg_order = list(target_leg_order)

    def is_identity(self) -> bool:
        return not self.target_leg_order

    def apply(self, tensor: LeafTensor) -> LeafTensor:
        if self.is_identity():
            return tensor
        if sorted(tensor.legs) != sorted(self.target_leg_order):
            raise ValueError(
                f"tensor legs {tensor.legs} are not a permutation of target "
                f"{self.target_leg_order}"
            )
        # axes[k] = position in `tensor.legs` of the k-th target leg
        pos = {leg: i for i, leg in enumerate(tensor.legs)}
        axes = [pos[leg] for leg in self.target_leg_order]
        data = np.transpose(tensor.data.into_data(), axes)
        bond_dims = [tensor.bond_dims[a] for a in axes]
        return LeafTensor(self.target_leg_order, bond_dims, TensorData.matrix(data))


def _ket0() -> TensorData:
    return TensorData.from_values((2,), [1.0 + 0.0j, 0.0 + 0.0j])


def _ket1() -> TensorData:
    return TensorData.from_values((2,), [0.0 + 0.0j, 1.0 + 0.0j])


class Circuit:
    """Tensor-network circuit builder (``circuit_builder.rs:127-134``)."""

    def __init__(self) -> None:
        self.open_edges: list[EdgeIndex] = []
        self.next_edge: int = 0
        self.tensor_network = CompositeTensor()
        self._finalized = False

    def _finalize(self) -> None:
        """Finalizers consume the builder (the reference takes ``self`` by
        value); a second finalizer call would corrupt the network.
        """
        if self._finalized:
            raise RuntimeError(
                "Circuit was already converted to a network; build a new Circuit"
            )
        self._finalized = True

    def _new_edge(self) -> EdgeIndex:
        edge = self.next_edge
        self.next_edge += 1
        return edge

    def num_qubits(self) -> int:
        return len(self.open_edges)

    def allocate_register(self, size: int) -> QuantumRegister:
        """Allocate ``size`` qubits initialized to |0⟩."""
        if self._finalized:
            raise RuntimeError("Circuit was already converted to a network")
        base = self.num_qubits()
        for _ in range(size):
            edge = self._new_edge()
            self.open_edges.append(edge)
            ket = LeafTensor.from_const([edge], 2)
            ket.data = _ket0()
            self.tensor_network.push_tensor(ket)
        return QuantumRegister(base, size)

    def append_gate(self, gate: TensorData, qubits: Sequence[Qubit]) -> None:
        """Append a gate tensor acting on ``qubits``; legs = new ++ old."""
        if self._finalized:
            raise RuntimeError("Circuit was already converted to a network")
        indices = [q.index for q in qubits]
        if len(set(indices)) != len(indices):
            raise ValueError("Qubit arguments must be unique")

        old_edges = [self.open_edges[i] for i in indices]
        new_edges = [self.next_edge + k for k in range(len(indices))]
        self.next_edge += len(indices)
        for qubit_index, new_edge in zip(indices, new_edges):
            self.open_edges[qubit_index] = new_edge

        tensor = LeafTensor.from_const(new_edges + old_edges, 2)
        tensor.data = gate
        self.tensor_network.push_tensor(tensor)

    # -- finalizers --------------------------------------------------------

    def into_amplitude_network(self, bitstring: str) -> tuple[CompositeTensor, Permutor]:
        """Close the circuit with ⟨0|/⟨1| bras per the bitstring; ``*``
        leaves the leg open (statevector slice). Returns the network and a
        Permutor for the open legs in qubit order.
        """
        if len(bitstring) != self.num_qubits():
            raise ValueError(
                f"bitstring length {len(bitstring)} != qubit count {self.num_qubits()}"
            )
        self._finalize()
        final_legs: list[EdgeIndex] = []
        for c, edge in zip(bitstring, self.open_edges):
            if c == "*":
                final_legs.append(edge)
                continue
            if c == "0":
                data = _ket0()
            elif c == "1":
                data = _ket1()
            else:
                raise ValueError("Only 0, 1 and * are allowed in bitstring")
            bra = LeafTensor.from_const([edge], 2)
            bra.data = data
            self.tensor_network.push_tensor(bra)
        return self.tensor_network, Permutor(final_legs)

    def into_statevector_network(self) -> tuple[CompositeTensor, Permutor]:
        return self.into_amplitude_network("*" * self.num_qubits())

    @staticmethod
    def _tensor_adjoint(tensor: LeafTensor, leg_offset: int) -> LeafTensor:
        """Adjoint with legs half-swapped and offset
        (``circuit_builder.rs:278-297``).
        """
        half = len(tensor.legs) // 2
        legs = [l + leg_offset for l in tensor.legs[half:] + tensor.legs[:half]]
        bond_dims = tensor.bond_dims[half:] + tensor.bond_dims[:half]
        return LeafTensor(legs, bond_dims, tensor.data.adjoint())

    def into_expectation_value_network(self) -> CompositeTensor:
        """⟨ψ|Z…Z|ψ⟩ network: circuit ++ adjoint mirror ++ Z layer
        (``circuit_builder.rs:304-326``).
        """
        self._finalize()
        offset = self.next_edge
        adjoints = [
            self._tensor_adjoint(t, offset) for t in self.tensor_network.tensors
        ]
        self.tensor_network.push_tensors(adjoints)
        for edge in self.open_edges:
            observable = LeafTensor.from_const([edge, edge + offset], 2)
            observable.data = TensorData.gate("z")
            self.tensor_network.push_tensor(observable)
        return self.tensor_network
