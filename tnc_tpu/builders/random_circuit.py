"""Random circuit generation.

Mirror of ``tnc/src/builders/random_circuit.rs``:

- :func:`random_circuit` — ``rounds`` rounds of Bernoulli-placed
  {sx, sy, sz} single-qubit gates and fsim(0.3, 0.2) two-qubit gates on a
  connectivity graph, closed as a |0…0⟩ amplitude network
  (``random_circuit.rs:29-80``).
- :func:`random_circuit_with_observable` /
  :func:`random_circuit_with_set_observable` — builds a ⟨O⟩
  expectation-value network *directly*: observables sit in the middle,
  each gate appears paired with its adjoint on the mirror side, and gates
  with no causal effect on any observable are skipped entirely
  (``random_circuit.rs:88-275``). Note: the reference pairs sy/sz with an
  sx adjoint on the mirror side (``random_circuit.rs:133-145``), which is
  an apparent copy-paste slip; here each gate is mirrored by its own
  adjoint so the network is a true expectation value.
"""

from __future__ import annotations

import numpy as np

from tnc_tpu.builders.circuit_builder import Circuit
from tnc_tpu.builders.connectivity import Connectivity, ConnectivityLayout
from tnc_tpu.builders.tensorgeneration import random_sparse_tensor_data_with_rng
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData

_SINGLE_QUBIT_GATES = ("sx", "sy", "sz")
_OBSERVABLES = ("x", "y", "z")
_FSIM_ANGLES = (0.3, 0.2)


def _filtered_connectivity(
    layout: ConnectivityLayout, qubits: int
) -> list[tuple[int, int]]:
    graph = Connectivity.new(layout, qubits)
    return [(u, v) for (u, v) in graph.connectivity if u < qubits and v < qubits]


def random_open_circuit(
    qubits: int,
    rounds: int,
    single_qubit_probability: float,
    two_qubit_probability: float,
    rng: np.random.Generator,
    connectivity: ConnectivityLayout,
) -> Circuit:
    """The unfinalized random circuit (gates only, no bras) — feed it to
    any finalizer, or to :func:`tnc_tpu.tensornetwork.amplitude_sweep`
    for batched bitstring evaluation."""
    connectivity_pairs = _filtered_connectivity(connectivity, qubits)

    circuit = Circuit()
    qr = circuit.allocate_register(qubits)

    for _ in range(1, rounds):
        for i in range(qubits):
            if rng.random() < single_qubit_probability:
                name = _SINGLE_QUBIT_GATES[int(rng.integers(0, 3))]
                circuit.append_gate(TensorData.gate(name), [qr.qubit(i)])
        for i, j in connectivity_pairs:
            if rng.random() < two_qubit_probability:
                circuit.append_gate(
                    TensorData.gate("fsim", _FSIM_ANGLES), [qr.qubit(i), qr.qubit(j)]
                )
    return circuit


def brickwork_circuit(
    qubits: int, depth: int, rng: np.random.Generator
) -> Circuit:
    """Dense brickwork circuit (H layer, then per-round random-angle Rz
    rotations + alternating CX bricks), unfinalized — the serving
    workload generator shared by ``bench.py --serve`` and
    ``scripts/serve_smoke.py`` (one recipe, so the smoke validates the
    same structure the perf record measures). Deterministic in ``rng``:
    same generator state → identical structure AND gate values."""
    angles = [
        [float(rng.uniform(0, 3)) for _ in range(qubits)]
        for _ in range(depth)
    ]
    return brickwork_from_angles(qubits, angles)


def brickwork_from_angles(
    qubits: int, round_angles: list[list[float]]
) -> Circuit:
    """The brickwork recipe with explicit per-round Rz angles —
    :func:`brickwork_circuit`'s builder, exposed so sweep workloads can
    pin a shared angle prefix across settings."""
    circuit = Circuit()
    qr = circuit.allocate_register(qubits)
    for q in range(qubits):
        circuit.append_gate(TensorData.gate("h"), [qr.qubit(q)])
    for d, angles in enumerate(round_angles):
        for q in range(qubits):
            circuit.append_gate(
                TensorData.gate("rz", (angles[q],)), [qr.qubit(q)]
            )
        for q in range(d % 2, qubits - 1, 2):
            circuit.append_gate(
                TensorData.gate("cx"), [qr.qubit(q), qr.qubit(q + 1)]
            )
    return circuit


def brickwork_sweep(
    qubits: int,
    depth: int,
    prefix_depth: int,
    settings: int,
    rng: np.random.Generator,
) -> list[Circuit]:
    """``settings`` brickwork angle settings of one ansatz sharing the
    first ``prefix_depth`` rounds' angles — the parameter-sweep serving
    workload (``BENCH_SERVE_SWEEP=angles:N``,
    ``scripts/reuse_smoke.py``): every circuit's contraction tree
    contains the same-valued prefix subtrees, so a cross-request
    :class:`~tnc_tpu.serve.reuse.IntermediateStore` contracts them once
    store-wide. Deterministic in ``rng``."""
    prefix_depth = max(0, min(int(prefix_depth), int(depth)))
    prefix = [
        [float(rng.uniform(0, 3)) for _ in range(qubits)]
        for _ in range(prefix_depth)
    ]
    out = []
    for _ in range(max(int(settings), 1)):
        suffix = [
            [float(rng.uniform(0, 3)) for _ in range(qubits)]
            for _ in range(depth - prefix_depth)
        ]
        out.append(brickwork_from_angles(qubits, prefix + suffix))
    return out


def random_circuit(
    qubits: int,
    rounds: int,
    single_qubit_probability: float,
    two_qubit_probability: float,
    rng: np.random.Generator,
    connectivity: ConnectivityLayout,
    bitstring: str | None = None,
) -> CompositeTensor:
    """Random circuit closed as an amplitude network.

    ``bitstring`` defaults to |0…0⟩ (the reference's behavior,
    ``random_circuit.rs:29-80``); pass ``"*" * qubits`` for an open
    statevector network.

    >>> import numpy as np
    >>> from tnc_tpu.builders.connectivity import ConnectivityLayout
    >>> tn = random_circuit(6, 4, 0.5, 0.5, np.random.default_rng(0),
    ...                     ConnectivityLayout.LINE)
    >>> tn.external_tensor().legs          # amplitude: fully closed
    []
    >>> sv = random_circuit(6, 4, 0.5, 0.5, np.random.default_rng(0),
    ...                     ConnectivityLayout.LINE, bitstring="*" * 6)
    >>> len(sv.external_tensor().legs)     # statevector: 6 open legs
    6
    """
    circuit = random_open_circuit(
        qubits,
        rounds,
        single_qubit_probability,
        two_qubit_probability,
        rng,
        connectivity,
    )
    if bitstring is None:
        bitstring = "0" * qubits
    return circuit.into_amplitude_network(bitstring)[0]


def random_circuit_with_observable(
    qubits: int,
    rounds: int,
    single_qubit_probability: float,
    two_qubit_probability: float,
    observable_probability: float,
    rng: np.random.Generator,
    connectivity: ConnectivityLayout,
) -> CompositeTensor:
    """Random ⟨O⟩ network with Bernoulli-placed observables."""
    observable_locations = [
        i for i in range(qubits) if rng.random() < observable_probability
    ]
    return random_circuit_with_set_observable(
        qubits,
        rounds,
        single_qubit_probability,
        two_qubit_probability,
        observable_locations,
        rng,
        connectivity,
    )


def random_circuit_with_set_observable(
    qubits: int,
    rounds: int,
    single_qubit_probability: float,
    two_qubit_probability: float,
    observable_location: list[int],
    rng: np.random.Generator,
    connectivity: ConnectivityLayout,
) -> CompositeTensor:
    """Random ⟨O⟩ network with observables on the given qubits.

    Gate placement walks *outward* from the observable layer: a qubit whose
    forward and backward edges coincide (no observable in its causal cone
    yet) contributes nothing, so gates there are skipped — the reference's
    light-cone optimization (``random_circuit.rs:190-255``).

    Each qubit's ``open_edges[i] = (left, right)`` tracks the next open leg
    on the circuit side (left) and the adjoint-mirror side (right).
    """
    tn = CompositeTensor()
    observable_set = set(observable_location)

    open_edges: dict[int, tuple[int, int]] = {}
    next_edge = 0

    # Observable layer in the middle.
    for i in range(qubits):
        if i in observable_set:
            open_edges[i] = (next_edge, next_edge + 1)
            name = _OBSERVABLES[int(rng.integers(0, 3))]
            obs = LeafTensor.from_const([next_edge, next_edge + 1], 2)
            obs.data = TensorData.gate(name)
            tn.push_tensor(obs)
            next_edge += 2
        else:
            open_edges[i] = (0, 0)  # sentinel: not yet in any causal cone

    connectivity_pairs = _filtered_connectivity(connectivity, qubits)

    for _ in range(1, rounds):
        # Two-qubit gates (and their mirror adjoints), only where they can
        # affect an observable.
        for i, j in connectivity_pairs:
            if rng.random() >= two_qubit_probability:
                continue
            i_open = open_edges[i][0] != open_edges[i][1]
            j_open = open_edges[j][0] != open_edges[j][1]
            if not (i_open or j_open):
                continue
            if i_open:
                left_i, right_i = open_edges[i]
            else:
                left_i = right_i = next_edge
                next_edge += 1
            if j_open:
                left_j, right_j = open_edges[j]
            else:
                left_j = right_j = next_edge
                next_edge += 1

            left = LeafTensor.from_const(
                [next_edge, next_edge + 1, left_i, left_j], 2
            )
            left.data = TensorData.gate("fsim", _FSIM_ANGLES)
            tn.push_tensor(left)

            right = LeafTensor.from_const(
                [right_i, right_j, next_edge + 2, next_edge + 3], 2
            )
            right.data = TensorData.gate("fsim", _FSIM_ANGLES, adjoint=True)
            tn.push_tensor(right)

            open_edges[i] = (next_edge, next_edge + 2)
            open_edges[j] = (next_edge + 1, next_edge + 3)
            next_edge += 4

        # Single-qubit gates + mirrored adjoints.
        for i in range(qubits):
            left_index, right_index = open_edges[i]
            if rng.random() < single_qubit_probability and left_index != right_index:
                name = _SINGLE_QUBIT_GATES[int(rng.integers(0, 3))]

                left = LeafTensor.from_const([next_edge, left_index], 2)
                left.data = TensorData.gate(name)
                tn.push_tensor(left)

                right = LeafTensor.from_const([right_index, next_edge + 1], 2)
                right.data = TensorData.gate(name, adjoint=True)
                tn.push_tensor(right)

                open_edges[i] = (next_edge, next_edge + 1)
                next_edge += 2

    # Random initial states, shared by circuit and mirror sides.
    for i in range(qubits):
        left_index, right_index = open_edges[i]
        if left_index != right_index:
            state = random_sparse_tensor_data_with_rng([2], 1.0, rng)

            left_state = LeafTensor.from_const([left_index], 2)
            left_state.data = state
            tn.push_tensor(left_state)

            right_state = LeafTensor.from_const([right_index], 2)
            right_state.data = TensorData.matrix(
                np.conj(state.into_data())
            )
            tn.push_tensor(right_state)

    return tn
