"""OOM-adaptive degradation ladder for sliced execution.

When the runtime throws ``RESOURCE_EXHAUSTED``, retrying the identical
program fails identically — the program has to shrink. The ladder, from
cheapest to most invasive:

1. **Smaller slice batch** — handled *inside* the chunked executor
   (:mod:`tnc_tpu.ops.chunked`): the per-device slice batch halves
   (recompiling only the chunk plan) and the run continues from the
   current cursor, down to batch 1.
2. **Finer slicing** — handled here: re-plan through the existing
   planner hook (:func:`~tnc_tpu.contractionpath.slicing.slice_and_reconfigure`)
   at a 4× smaller element target, rebuild the sliced program, re-run.
3. **Chunked host-loop fallback** — if the backend was using the
   single-dispatch on-device loop (``sliced_strategy="loop"``), fall
   back to the chunked host-loop executor at batch 1, the
   smallest-footprint executor in the stack.

Every rung is visible through obs (``resilience.ladder.*`` counters and
gauges, plus the warning log), so a production run that survived an OOM
says exactly how much performance it paid.
"""

from __future__ import annotations

import logging

import numpy as np

from tnc_tpu import obs
from tnc_tpu.resilience.retry import FailureClass, classify_exception

logger = logging.getLogger(__name__)


def execute_sliced_resilient(
    tn,
    contract_path,
    slicing,
    arrays=None,
    backend=None,
    max_replans: int = 2,
    max_slices: int | None = None,
    host: bool = True,
):
    """Run a sliced contraction, walking the degradation ladder on
    RESOURCE_EXHAUSTED instead of crashing.

    ``tn`` + flat ``contract_path`` + initial ``slicing`` describe the
    network exactly as :func:`~tnc_tpu.ops.sliced.build_sliced_program`
    consumes them (the network-level inputs are required because rung 2
    re-plans the slicing). Returns ``(result, slicing_used)`` — the
    slicing may be finer than requested after degradation.

    Transient failures are retried at the dispatch boundaries below this
    level; FATAL errors re-raise untouched.

    >>> import numpy as np
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> from tnc_tpu.contractionpath.slicing import Slicing
    >>> from tnc_tpu.ops.backends import NumpyBackend
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> rng = np.random.default_rng(0)
    >>> def mk(legs):
    ...     return LeafTensor(legs, [2] * len(legs),
    ...         TensorData.matrix(rng.standard_normal([2] * len(legs))))
    >>> tn = CompositeTensor([mk([0, 1]), mk([1, 2]), mk([2, 0])])
    >>> path = ContractionPath.simple([(0, 1), (0, 2)])
    >>> out, used = execute_sliced_resilient(
    ...     tn, path, Slicing((2,), (2,)), backend=NumpyBackend())
    >>> used.num_slices, out.shape
    (2, ())
    """
    from tnc_tpu.contractionpath.contraction_path import (
        ContractionPath,
        replace_ssa_ordering,
    )
    from tnc_tpu.contractionpath.slicing import slice_and_reconfigure
    from tnc_tpu.ops.backends import JaxBackend
    from tnc_tpu.ops.budget import program_peak_bytes
    from tnc_tpu.ops.program import flat_leaf_tensors
    from tnc_tpu.ops.sliced import build_sliced_program

    if contract_path.nested:
        raise ValueError(
            "execute_sliced_resilient expects a flat path; the partitioned "
            "executors carry their own per-partition recovery"
        )
    if backend is None:
        backend = JaxBackend()
    leaves = flat_leaf_tensors(tn)
    if arrays is None:
        arrays = [np.asarray(l.data.into_data()) for l in leaves]

    sp = build_sliced_program(tn, contract_path, slicing)
    ssa = replace_ssa_ordering(list(contract_path.toplevel), len(leaves))
    target: float | None = None
    replans = 0
    with obs.span("resilience.ladder") as osp:
        while True:
            try:
                out = backend.execute_sliced(
                    sp, arrays, max_slices=max_slices, host=host
                )
                osp.set(replans=replans, slices=sp.slicing.num_slices)
                return out, sp.slicing
            except Exception as exc:  # noqa: BLE001 — classified below
                if classify_exception(exc) is not FailureClass.RESOURCE:
                    raise
                if replans >= max_replans:
                    if getattr(backend, "sliced_strategy", None) == "loop":
                        # final rung: chunked host loop, batch 1 — the
                        # smallest-footprint executor available
                        logger.warning(
                            "degradation ladder: falling back to the "
                            "chunked host-loop executor at batch 1"
                        )
                        obs.counter_add("resilience.ladder.fallback_chunked")
                        fb = JaxBackend(
                            dtype=backend.dtype,
                            device=backend.device,
                            split_complex=backend.split_complex,
                            precision=backend.precision,
                            sliced_strategy="chunked",
                            slice_batch=1,
                            chunk_steps=backend.chunk_steps,
                            hoist=backend.hoist,
                        )
                        out = fb.execute_sliced(
                            sp, arrays, max_slices=max_slices, host=host
                        )
                        osp.set(replans=replans, fallback="chunked")
                        return out, sp.slicing
                    raise
                # rung 2: re-slice finer through the planner hook
                replans += 1
                if target is None:
                    est = program_peak_bytes(sp.program)
                    target = 2.0 ** np.floor(
                        np.log2(max(est.peak_bytes / 8.0 / 4.0, 4.0))
                    )
                else:
                    target = max(target / 4.0, 4.0)
                obs.counter_add("resilience.ladder.replans")
                logger.warning(
                    "degradation ladder: RESOURCE_EXHAUSTED (%s); "
                    "re-slicing finer at target %g elements (replan %d/%d)",
                    exc, target, replans, max_replans,
                )
                pairs, new_slicing = slice_and_reconfigure(
                    leaves, ssa, target,
                    reconf_rounds=1, step_budget=None,
                    final_rounds=2, final_budget=None,
                )
                if not new_slicing.legs:
                    # target still above the peak: push it down and retry
                    target = max(target / 4.0, 4.0)
                    pairs, new_slicing = slice_and_reconfigure(
                        leaves, ssa, target,
                        reconf_rounds=1, step_budget=None,
                        final_rounds=2, final_budget=None,
                    )
                sp = build_sliced_program(
                    tn, ContractionPath.simple(pairs), new_slicing
                )
                obs.gauge_set(
                    "resilience.ladder.num_slices", new_slicing.num_slices
                )
