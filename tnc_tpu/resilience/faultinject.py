"""Deterministic fault injection at the execution-stack boundaries.

Nothing in a CPU test suite can make XLA throw ``RESOURCE_EXHAUSTED`` or
a preemption notice on demand, so every recovery path in
:mod:`tnc_tpu.resilience` would otherwise be dead code until a real TPU
failed at slice 10^8. This module plants named **fault points** at the
same boundaries the retry/degrade machinery guards; a scripted spec
makes chosen points raise (or SIGKILL the process) a fixed number of
times, deterministically.

Env-gated like :mod:`tnc_tpu.obs`: with ``TNC_TPU_FAULTS`` unset,
:func:`fault_point` is one module-level bool check (pinned by
``tests/test_resilience.py``'s overhead test).

Spec DSL (``TNC_TPU_FAULTS`` or :func:`configure_faults`): rules
separated by ``;``, each

    site(key=value, ...) = kind * count

- ``site`` — the fault-point name (``chunked.batch``, ``chunked.plan``,
  ``backend.dispatch``, ``spmd.dispatch``, ``partition.local``,
  ``sliced.slice``, and the cluster-serving boundaries:
  ``cluster.worker`` — per-round (``phase=round, process=``) and
  per-slice (``phase=slice, s=, process=``) on the worker loop, the
  elastic kill-pin's SIGKILL site — and ``cluster.broadcast``
  (``side=root, seq=`` on the dispatcher, ``side=worker, process=`` on
  the parked loop), where a ``slow`` rule holds a collective round
  open against ``stop()``'s drain).
- ``(key=value, ...)`` — optional match on the call-site context
  (compared as strings): ``chunked.batch(start=8)`` fires only for the
  batch starting at slice 8; ``partition.local(partition=1)`` kills
  partition 1 only.
- ``kind`` — ``oom`` (raises with a ``RESOURCE_EXHAUSTED`` message →
  classified RESOURCE), ``transient``/``preempt`` (``UNAVAILABLE:
  injected preemption`` → TRANSIENT), ``fatal`` (``INTERNAL`` →
  FATAL), ``kill`` (SIGKILL the process — crash-resume smokes), or
  ``slow[:seconds]`` (sleep instead of raise — the SLO smoke's
  injected slowdown; default 0.05 s, e.g. ``serve.dispatch=slow:0.2*-1``).
- ``* count`` — how many times the rule fires (default 1; ``*-1`` =
  unlimited).

>>> with faults("demo.site(x=1)=oom*1"):
...     fault_point("demo.site", x=0)   # condition mismatch: no fire
...     try:
...         fault_point("demo.site", x=1)
...     except InjectedOOM as e:
...         print("fired:", "RESOURCE_EXHAUSTED" in str(e))
...     fault_point("demo.site", x=1)   # count exhausted: no fire
fired: True
>>> fault_point("demo.site", x=1)       # disabled outside the context
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
from dataclasses import dataclass

from tnc_tpu import obs

logger = logging.getLogger(__name__)


class InjectedFault(RuntimeError):
    """Base class for injected failures (never raised itself)."""


class InjectedOOM(InjectedFault):
    """Classified RESOURCE by :func:`~tnc_tpu.resilience.retry.classify_exception`."""


class InjectedTransient(InjectedFault):
    """Classified TRANSIENT — an injected preemption/disconnect."""


class InjectedFatal(InjectedFault):
    """Classified FATAL — an injected unrecoverable error."""


_KINDS = {
    "oom": (
        InjectedOOM,
        "RESOURCE_EXHAUSTED: injected out of memory at {site}",
    ),
    "transient": (
        InjectedTransient,
        "UNAVAILABLE: injected preemption at {site}",
    ),
    "preempt": (
        InjectedTransient,
        "UNAVAILABLE: injected preemption at {site}",
    ),
    "fatal": (
        InjectedFatal,
        "INTERNAL: injected fatal failure at {site}",
    ),
    "kill": (None, None),  # SIGKILL, no exception to raise
    "slow": (None, None),  # sleep, no exception — latency injection
}

_SLOW_DEFAULT_S = 0.05


@dataclass
class _Rule:
    site: str
    conds: dict[str, str]
    kind: str
    remaining: int  # -1 = unlimited
    arg: float = 0.0  # kind parameter (sleep seconds for ``slow``)


_RULES: list[_Rule] = []
_ENABLED = False
_LOCK = threading.Lock()


def parse_spec(spec: str) -> list[_Rule]:
    """Parse the DSL; raises ``ValueError`` on malformed rules so typos
    in ``TNC_TPU_FAULTS`` fail loudly instead of silently injecting
    nothing."""
    rules: list[_Rule] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if "=" not in raw.split("(")[0] and "=" not in raw.rsplit(")", 1)[-1]:
            raise ValueError(f"fault rule missing '=kind': {raw!r}")
        # split 'site(conds)' from 'kind*count' at the LAST top-level '='
        depth = 0
        eq = -1
        for i, ch in enumerate(raw):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "=" and depth == 0:
                eq = i
        if eq < 0:
            raise ValueError(f"fault rule missing '=kind': {raw!r}")
        left, right = raw[:eq].strip(), raw[eq + 1:].strip()
        count = 1
        if "*" in right:
            kind, _, cnt = right.partition("*")
            kind = kind.strip()
            count = int(cnt.strip())
        else:
            kind = right
        arg = 0.0
        if kind.startswith("slow"):
            base, _, dur = kind.partition(":")
            if base != "slow":
                raise ValueError(f"unknown fault kind {kind!r}")
            arg = float(dur) if dur else _SLOW_DEFAULT_S
            if arg < 0.0:
                raise ValueError(f"slow duration must be >= 0: {kind!r}")
            kind = "slow"
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; one of {sorted(_KINDS)}"
            )
        conds: dict[str, str] = {}
        site = left
        if "(" in left:
            if not left.endswith(")"):
                raise ValueError(f"unbalanced conditions in {raw!r}")
            site, _, inner = left.partition("(")
            for pair in inner[:-1].split(","):
                pair = pair.strip()
                if not pair:
                    continue
                if "=" not in pair:
                    raise ValueError(f"bad condition {pair!r} in {raw!r}")
                k, _, v = pair.partition("=")
                conds[k.strip()] = v.strip()
        if not site.strip():
            raise ValueError(f"fault rule missing site: {raw!r}")
        rules.append(_Rule(site.strip(), conds, kind, count, arg))
    return rules


def configure_faults(spec: str | None) -> None:
    """Install a fault script (None/empty disables injection)."""
    global _RULES, _ENABLED
    with _LOCK:
        _RULES = parse_spec(spec) if spec else []
        _ENABLED = bool(_RULES)


def refresh_from_env() -> bool:
    """Re-read ``TNC_TPU_FAULTS`` (import-time default)."""
    configure_faults(os.environ.get("TNC_TPU_FAULTS"))
    return _ENABLED


def enabled() -> bool:
    return _ENABLED


@contextlib.contextmanager
def faults(spec: str | None):
    """Scoped fault script for tests; restores the previous script."""
    global _RULES, _ENABLED
    with _LOCK:
        prev_rules, prev_enabled = _RULES, _ENABLED
    configure_faults(spec)
    try:
        yield
    finally:
        with _LOCK:
            _RULES, _ENABLED = prev_rules, prev_enabled


def fault_point(site: str, **ctx) -> None:
    """Declare an injectable boundary. Disabled path: one bool check.

    When a matching armed rule exists, decrements its count and raises
    the scripted error (or SIGKILLs the process for ``kill`` — the
    crash-resume smoke's deterministic "preemption mid-range").
    """
    if not _ENABLED:
        return
    _fire(site, ctx)


def _fire(site: str, ctx: dict) -> None:
    with _LOCK:
        rule = None
        for r in _RULES:
            if r.site != site or r.remaining == 0:
                continue
            if all(str(ctx.get(k)) == v for k, v in r.conds.items()):
                rule = r
                break
        if rule is None:
            return
        if rule.remaining > 0:
            rule.remaining -= 1
    obs.counter_add("resilience.faults.fired", site=site, kind=rule.kind)
    logger.warning(
        "faultinject: firing %s at %s (ctx=%s)", rule.kind, site, ctx
    )
    if rule.kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        return  # pragma: no cover — unreachable
    if rule.kind == "slow":
        import time

        time.sleep(rule.arg)
        return
    exc_type, msg = _KINDS[rule.kind]
    raise exc_type(msg.format(site=site))


refresh_from_env()
