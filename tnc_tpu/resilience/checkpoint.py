"""Atomic slice-range checkpoints for the sliced executors.

A deeply sliced contraction is a sum of independent slice contributions
accumulated in a fixed order; everything needed to resume after a crash
is (1) the partial accumulator (the Kahan (sum, comp) pairs in the
chunked executor), (2) the next-slice cursor, and (3) a signature of the
program + execution parameters so a checkpoint is never resumed into a
different computation. This module persists exactly that, atomically
(write-to-temp + fsync + ``os.replace``), as a single ``.npz``.

Gating: the executors take an explicit ``ckpt=`` argument, falling back
to the ``TNC_TPU_CKPT`` env var (:func:`resolve_ckpt`); unset means no
checkpoint object is ever constructed — the hot-path cost is one dict
lookup per *execution call* (not per slice), pinned by
``tests/test_resilience.py``.

``TNC_TPU_CKPT`` names a **directory** (created on demand): each
distinct program signature writes its own ``ckpt_<sig>.npz``, so the
parity oracle and the device run sharing one process never clobber each
other. A value ending in ``.npz`` is used as an exact file path.

Cadence (:meth:`SliceCheckpoint.maybe_save`): every
``TNC_TPU_CKPT_EVERY`` slices if set, else every ``TNC_TPU_CKPT_SECS``
seconds (default 30 — a checkpoint costs a device→host transfer of the
accumulator, which is result-shaped, i.e. tiny, but the sync stalls the
async dispatch pipeline). Completed runs delete their checkpoint
(:meth:`finalize`), so a finished result is never "resumed".

Resume is **bit-identical**: the accumulator round-trips exactly
(float arrays, no re-encoding) and the remaining slices accumulate in
the same order with the same compiled kernels.

>>> import tempfile, numpy as np, os
>>> d = tempfile.mkdtemp()
>>> ck = SliceCheckpoint(d, "sig-a", every=1)
>>> ck.load() is None
True
>>> ck.maybe_save(4, lambda: [np.arange(3.0)])
True
>>> cursor, arrs = SliceCheckpoint(d, "sig-a").load()
>>> cursor, [float(x) for x in arrs[0]]
(4, [0.0, 1.0, 2.0])
>>> SliceCheckpoint(d, "sig-OTHER").load() is None  # signature mismatch
True
>>> ck.finalize(); SliceCheckpoint(d, "sig-a").load() is None
True
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from pathlib import Path
from typing import Any, Callable, Sequence

import numpy as np

from tnc_tpu import obs

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1


def resolve_ckpt(arg: str | None = None) -> str | None:
    """Explicit argument wins; else ``TNC_TPU_CKPT``; else None (off)."""
    if arg:
        return arg
    return os.environ.get("TNC_TPU_CKPT") or None


def signature_hash(*parts: Any) -> str:
    """Stable digest of the program + execution parameters a checkpoint
    is only valid for. Delegates to the shared canonical encoder
    (:func:`tnc_tpu.utils.digest.stable_digest`) so checkpoint
    signatures, benchmark cache keys, and the serving plan cache all
    hash program state the same way — and the digest no longer depends
    on ``repr`` (dict ordering / hash seeds)."""
    from tnc_tpu.utils.digest import stable_digest

    return stable_digest(*parts)


def arrays_digest(arrays) -> str:
    """Digest of the input tensors' shapes, dtypes, and bytes. Folded
    into the checkpoint signature because the program signature alone is
    structural: two runs of the same circuit with different leaf data
    (e.g. amplitude networks for different bitstrings) share it, and one
    must never resume the other's accumulator. Only computed when
    checkpointing is armed, from host-resident arrays (never forces a
    device transfer)."""
    h = hashlib.sha256()
    for a in arrays:
        a = np.asarray(a)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


class SliceCheckpoint:
    """One checkpoint slot for one (program, params) signature."""

    def __init__(
        self,
        path: str | Path,
        signature: str,
        every: int | None = None,
        min_interval_s: float | None = None,
    ):
        path = Path(path)
        if path.suffix == ".npz":
            self.file = path
        else:
            self.file = path / f"ckpt_{signature[:16]}.npz"
        self.signature = signature
        if every is None:
            raw = os.environ.get("TNC_TPU_CKPT_EVERY")
            every = int(raw) if raw else None
        self.every = every
        if min_interval_s is None:
            min_interval_s = float(os.environ.get("TNC_TPU_CKPT_SECS", "30"))
        self.min_interval_s = min_interval_s
        self._last_cursor = 0
        self._last_t = time.monotonic()

    def load(self) -> tuple[int, list[np.ndarray]] | None:
        """(cursor, accumulator arrays) or None (absent / corrupt /
        signature mismatch — each logged, never raised: a bad checkpoint
        degrades to a fresh run)."""
        if not self.file.exists():
            return None
        try:
            with np.load(self.file, allow_pickle=False) as z:
                meta = json.loads(str(z["meta"]))
                arrays = [z[f"a{i}"] for i in range(meta["n"])]
        except Exception as exc:  # noqa: BLE001 — any corruption → fresh
            logger.warning(
                "checkpoint %s unreadable (%s: %s); starting fresh",
                self.file, type(exc).__name__, exc,
            )
            return None
        if meta.get("version") != FORMAT_VERSION:
            logger.warning(
                "checkpoint %s has format version %s (want %d); ignoring",
                self.file, meta.get("version"), FORMAT_VERSION,
            )
            return None
        if meta.get("signature") != self.signature:
            logger.warning(
                "checkpoint %s signature mismatch (program or execution "
                "parameters changed); starting fresh", self.file,
            )
            return None
        cursor = int(meta["cursor"])
        obs.counter_add("resilience.ckpt.resumed")
        logger.info(
            "resuming from checkpoint %s at slice cursor %d",
            self.file, cursor,
        )
        self._last_cursor = cursor
        return cursor, arrays

    def save(self, cursor: int, arrays: Sequence[Any]) -> None:
        """Atomic write: temp file in the same directory, fsync,
        ``os.replace``. A SIGKILL at any instant leaves either the old
        or the new checkpoint, never a torn one."""
        self.file.parent.mkdir(parents=True, exist_ok=True)
        meta = {
            "version": FORMAT_VERSION,
            "signature": self.signature,
            "cursor": int(cursor),
            "n": len(arrays),
        }
        payload = {
            f"a{i}": np.asarray(a) for i, a in enumerate(arrays)
        }
        tmp = self.file.with_suffix(".npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, meta=json.dumps(meta), **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.file)
        obs.counter_add("resilience.ckpt.saved")
        self._last_cursor = int(cursor)
        self._last_t = time.monotonic()

    def maybe_save(
        self, cursor: int, arrays_fn: Callable[[], Sequence[Any]]
    ) -> bool:
        """Cadence-gated :meth:`save`. ``arrays_fn`` is only called when
        a save actually happens (materializing the accumulator on the
        host costs a device sync)."""
        due = False
        if self.every is not None:
            due = cursor - self._last_cursor >= self.every
        elif self.min_interval_s is not None:
            due = time.monotonic() - self._last_t >= self.min_interval_s
        if not due:
            return False
        self.save(cursor, arrays_fn())
        return True

    def finalize(self) -> None:
        """Remove the checkpoint (run completed)."""
        try:
            self.file.unlink(missing_ok=True)
        except OSError:  # pragma: no cover — unwritable dir at exit
            pass
