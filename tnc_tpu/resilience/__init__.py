"""tnc_tpu.resilience — fault-tolerant execution for long-running jobs.

Four pieces, threaded through the execution stack (see
``docs/resilience.md``):

- :mod:`~tnc_tpu.resilience.retry` — exception classification
  (TRANSIENT / RESOURCE / FATAL) + the shared bounded-backoff
  :class:`RetryPolicy` applied at every device-dispatch boundary.
- :mod:`~tnc_tpu.resilience.checkpoint` — atomic slice-range
  checkpoints (``TNC_TPU_CKPT``): the chunked/numpy sliced executors
  persist the partial accumulator + next-slice cursor and resume
  bit-identically after a crash.
- :mod:`~tnc_tpu.resilience.degrade` — the OOM degradation ladder
  (smaller slice batch → finer slicing → chunked host-loop fallback).
- :mod:`~tnc_tpu.resilience.faultinject` — deterministic scripted
  failures (``TNC_TPU_FAULTS``) at the same boundaries, so every
  recovery path above is unit-testable on CPU.

Everything is env/arg-gated with a no-op fast path; with no resilience
env vars set the hot paths pay one bool/dict check (pinned by
``tests/test_resilience.py``).
"""

from tnc_tpu.resilience.checkpoint import (  # noqa: F401
    SliceCheckpoint,
    resolve_ckpt,
    signature_hash,
)
from tnc_tpu.resilience.degrade import execute_sliced_resilient  # noqa: F401
from tnc_tpu.resilience.faultinject import (  # noqa: F401
    InjectedFault,
    InjectedFatal,
    InjectedOOM,
    InjectedTransient,
    configure_faults,
    fault_point,
    faults,
)
from tnc_tpu.resilience.retry import (  # noqa: F401
    FailureClass,
    RetryExhaustedError,
    RetryPolicy,
    buffers_alive,
    classify_exception,
    classify_pool_failure,
    configure_retry,
    default_policy,
    donation_guarded_classify,
    pool_map_with_retry,
    retry_call,
)
