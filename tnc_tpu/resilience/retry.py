"""Failure classification + bounded retry for device-dispatch boundaries.

The reference's answer to a failed rank is "restart the MPI job"; a
production jax_graft service running hours of deeply sliced contraction
on preemptible TPUs needs the opposite: classify what the runtime threw
and keep as much finished work as possible. Three classes
(:class:`FailureClass`):

- ``TRANSIENT`` — preemption notices, ICI/DCN hiccups, disconnects,
  deadline/timeout errors: safe to retry the same dispatch after a
  backoff (the work is deterministic and no state was consumed).
- ``RESOURCE`` — ``RESOURCE_EXHAUSTED`` / OOM: retrying the identical
  program will fail identically; the caller must *degrade* (smaller
  slice batch, finer slicing, chunked fallback — see
  :mod:`tnc_tpu.resilience.degrade` and the ladder inside
  :mod:`tnc_tpu.ops.chunked`).
- ``FATAL`` — everything else (shape errors, bugs): re-raise
  immediately, retrying a deterministic failure only hides it.

Classification is message/type-based because JAX surfaces all runtime
failures as ``XlaRuntimeError`` with a gRPC-style status prefix; the
injected faults (:mod:`tnc_tpu.resilience.faultinject`) carry the same
prefixes so every recovery path is exercisable on CPU.

:class:`RetryPolicy` is the shared bounded-attempts/exponential-backoff
engine applied at the dispatch boundaries (``ops/backends.py``,
``ops/chunked.py``, ``parallel/sliced_parallel.py``, per-partition in
``parallel/partitioned.py``) and to the repartitioning search pools.
Every retry is visible as ``resilience.retry`` obs counters.

>>> classify_exception(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
<FailureClass.RESOURCE: 'resource'>
>>> classify_exception(ConnectionResetError("peer vanished"))
<FailureClass.TRANSIENT: 'transient'>
>>> classify_exception(ValueError("bad shape"))
<FailureClass.FATAL: 'fatal'>
"""

from __future__ import annotations

import enum
import logging
import os
import random
import re
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tnc_tpu import obs

logger = logging.getLogger(__name__)


class FailureClass(enum.Enum):
    TRANSIENT = "transient"
    RESOURCE = "resource"
    FATAL = "fatal"


# Substrings matched (case-insensitively) against "TypeName: message".
_RESOURCE_PATTERNS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "failed to allocate",
    "allocation failure",
)
# "oom" needs word boundaries: a bare substring would classify any
# message containing "room"/"zoom"/"bloom" as RESOURCE and send a fatal
# bug through the degradation ladder
_OOM_RE = re.compile(r"\boom\b")
_TRANSIENT_PATTERNS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "cancelled",
    "preempt",
    "disconnect",
    "connection reset",
    "connection refused",
    "connection closed",
    "socket closed",
    "broken pipe",
    "heartbeat",
)
_TRANSIENT_TYPES = (ConnectionError, TimeoutError, BrokenPipeError)


def classify_exception(exc: BaseException) -> FailureClass:
    """Map an exception to the retry/degrade/re-raise decision.

    Checks the exception (and, for wrappers, its ``__cause__`` chain) by
    type and by the gRPC-style status text JAX puts in
    ``XlaRuntimeError`` messages. RESOURCE beats TRANSIENT when both
    match — an OOM wrapped in an ABORTED status must degrade, not spin.

    :class:`RetryExhaustedError` is FATAL by definition: its retries are
    already spent, and letting an outer dispatch boundary classify the
    embedded transient text as TRANSIENT would stack retry ladders
    (``max_attempts²`` dispatches through nested boundaries).
    """
    seen = 0
    cur: BaseException | None = exc
    while cur is not None and seen < 4:  # short cause chains only
        if isinstance(cur, RetryExhaustedError):
            # checked anywhere in the chain: a wrapped exhausted ladder
            # (e.g. inside PartitionExecutionError) must not re-match
            # the transient text embedded in its message
            return FailureClass.FATAL
        text = f"{type(cur).__name__}: {cur}".lower()
        if any(p in text for p in _RESOURCE_PATTERNS) or _OOM_RE.search(text):
            return FailureClass.RESOURCE
        if isinstance(cur, _TRANSIENT_TYPES) or any(
            p in text for p in _TRANSIENT_PATTERNS
        ):
            return FailureClass.TRANSIENT
        # multiprocessing.TimeoutError does not subclass TimeoutError
        if type(cur).__name__ == "TimeoutError":
            return FailureClass.TRANSIENT
        cur = cur.__cause__
        seen += 1
    return FailureClass.FATAL


class RetryExhaustedError(RuntimeError):
    """All retry attempts failed; carries the attempt count and chains
    the original error (``__cause__``)."""

    def __init__(self, label: str, attempts: int, last: BaseException):
        super().__init__(
            f"{label}: retries exhausted after {attempts} attempt"
            f"{'s' if attempts != 1 else ''}; last error: "
            f"{type(last).__name__}: {last}"
        )
        self.label = label
        self.attempts = attempts
        self.last = last


@dataclass
class RetryPolicy:
    """Bounded attempts with exponential backoff + jitter.

    ``run(fn)`` retries TRANSIENT failures (and RESOURCE when
    ``retry_resource=True`` — off by default: an identical OOM repeats
    identically, degrading is the caller's job); FATAL and unreclassified
    errors re-raise immediately. Exhaustion raises
    :class:`RetryExhaustedError` chained to the original.

    >>> calls = []
    >>> def flaky():
    ...     calls.append(1)
    ...     if len(calls) < 3:
    ...         raise ConnectionResetError("blip")
    ...     return "ok"
    >>> RetryPolicy(max_attempts=3, base_delay_s=0.0).run(flaky)
    'ok'
    >>> len(calls)
    3
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0
    jitter: float = 0.25
    retry_resource: bool = False
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.base_delay_s * (2.0 ** (attempt - 1)), self.max_delay_s)
        return d * (1.0 + self.jitter * rng.random())

    def run(
        self,
        fn: Callable[[], Any],
        label: str = "dispatch",
        classify: Callable[[BaseException], FailureClass] = classify_exception,
    ) -> Any:
        rng: random.Random | None = None  # seeded only if something fails
        last: BaseException | None = None
        for attempt in range(1, max(1, self.max_attempts) + 1):
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                cls = classify(exc)
                retryable = cls is FailureClass.TRANSIENT or (
                    cls is FailureClass.RESOURCE and self.retry_resource
                )
                obs.counter_add(
                    "resilience.retry.errors", site=label, cls=cls.value
                )
                if not retryable:
                    raise
                last = exc
                if attempt < max(1, self.max_attempts):
                    if rng is None:
                        rng = random.Random()
                    d = self.delay_s(attempt, rng)
                    obs.counter_add("resilience.retry.attempts", site=label)
                    logger.warning(
                        "%s failed (%s: %s; classified %s); retry %d/%d "
                        "in %.2fs",
                        label, type(exc).__name__, exc, cls.value,
                        attempt, self.max_attempts - 1, d,
                    )
                    self.sleep(d)
        assert last is not None
        obs.counter_add("resilience.retry.exhausted", site=label)
        raise RetryExhaustedError(label, max(1, self.max_attempts), last) from last


_DEFAULT_POLICY: RetryPolicy | None = None


def default_policy() -> RetryPolicy:
    """Process-wide policy for dispatch boundaries, built once from env:
    ``TNC_TPU_RETRY_ATTEMPTS`` (3), ``TNC_TPU_RETRY_BASE_S`` (0.1),
    ``TNC_TPU_RETRY_MAX_S`` (5.0)."""
    global _DEFAULT_POLICY
    if _DEFAULT_POLICY is None:
        _DEFAULT_POLICY = RetryPolicy(
            max_attempts=int(os.environ.get("TNC_TPU_RETRY_ATTEMPTS", "3")),
            base_delay_s=float(os.environ.get("TNC_TPU_RETRY_BASE_S", "0.1")),
            max_delay_s=float(os.environ.get("TNC_TPU_RETRY_MAX_S", "5.0")),
        )
    return _DEFAULT_POLICY


def configure_retry(policy: RetryPolicy | None) -> None:
    """Override (or, with None, re-derive from env) the default policy —
    tests use tiny backoffs."""
    global _DEFAULT_POLICY
    _DEFAULT_POLICY = policy


def retry_call(fn: Callable[[], Any], label: str = "dispatch") -> Any:
    """``default_policy().run(fn)`` — the one-liner the dispatch
    boundaries use. The fast path (no exception) costs one extra frame."""
    return default_policy().run(fn, label=label)


def sync_dispatch() -> bool:
    """``TNC_TPU_SYNC_DISPATCH=1``: dispatch boundaries block until the
    device result is ready, so asynchronously-surfacing runtime failures
    (JAX dispatch is async — a device error normally raises at the NEXT
    use of the poisoned value, outside the guarded region) land inside
    the retry/degradation scope. Off by default: the per-dispatch sync
    costs the host/device pipelining overlap, and without it a real
    async failure degrades to the pre-resilience behavior (propagate and
    crash; an armed checkpoint still resumes) rather than anything
    worse."""
    return os.environ.get("TNC_TPU_SYNC_DISPATCH", "").lower() in (
        "1", "true", "yes", "on",
    )


def buffers_alive(buffers) -> bool:
    """True when no (possibly (re, im)-paired) device buffer has been
    deleted — e.g. consumed by a donating dispatch. Duck-typed on
    ``is_deleted`` so host arrays pass trivially."""
    for buf in buffers:
        parts = buf if isinstance(buf, tuple) else (buf,)
        for part in parts:
            is_deleted = getattr(part, "is_deleted", None)
            if is_deleted is not None and is_deleted():
                return False
    return True


def donation_guarded_classify(buffers) -> Callable[[BaseException], FailureClass]:
    """Classifier for dispatch boundaries whose inputs may be donated:
    once a failed dispatch consumed them, a retry would re-dispatch
    deleted arrays and mask the original error — TRANSIENT downgrades to
    FATAL when any input buffer is gone. The ONE implementation of that
    invariant, shared by ``ops/backends.py`` and the per-partition
    boundary in ``parallel/partitioned.py``."""

    def _classify(exc: BaseException) -> FailureClass:
        cls = classify_exception(exc)
        if cls is FailureClass.TRANSIENT and not buffers_alive(buffers):
            return FailureClass.FATAL
        return cls

    return _classify


def classify_pool_failure(
    exc: BaseException, log: logging.Logger, what: str, can_retry: bool
) -> bool:
    """Shared handling for search-pool failures (genetic / simulated
    annealing): log the real worker error at warning level together with
    the fallback decision (the old ``except Exception: pool.terminate()``
    swallowed it), and return True when the caller should rebuild the
    pool and retry once (TRANSIENT only — and the caller must use a
    FRESH pool: the common transient is a hung worker timing out
    ``map_async().get``, and re-submitting to the wedged pool just burns
    a second timeout) before falling back to serial evaluation."""
    cls = classify_exception(exc)
    retry = can_retry and cls is FailureClass.TRANSIENT
    log.warning(
        "%s failed (%s: %s; classified %s); %s",
        what,
        type(exc).__name__,
        exc,
        cls.value,
        "recreating the pool and retrying once" if retry
        else "falling back to serial evaluation",
    )
    obs.counter_add("resilience.pool_failures", what=what, cls=cls.value)
    return retry


def pool_map_with_retry(pool, submit, rebuild, log: logging.Logger, what: str):
    """The one pool-failure loop shared by the repartitioning searches:
    run ``submit(pool)``; on a TRANSIENT failure terminate the (possibly
    wedged) pool, ``rebuild()`` a fresh one, and retry the same jobs
    once (results are pure functions of the jobs, so the retry is
    exact); anything else — or a second failure — terminates the pool
    and signals serial fallback.

    Returns ``(results, pool)``: ``results`` is None when the caller
    must evaluate serially, and ``pool`` is the surviving pool (None
    once failed over)."""
    attempt = 1
    while pool is not None:
        try:
            return submit(pool), pool
        except Exception as exc:  # noqa: BLE001 — classified below
            pool.terminate()
            if classify_pool_failure(exc, log, what, can_retry=attempt == 1):
                attempt += 1
                try:
                    pool = rebuild()
                except Exception as rexc:  # noqa: BLE001 — degrade, never crash
                    # respawning can fail under the same resource
                    # pressure that wedged the first pool (fork/fd
                    # exhaustion); the search must still complete
                    log.warning(
                        "%s rebuild failed (%s: %s); falling back to "
                        "serial evaluation",
                        what, type(rexc).__name__, rexc,
                    )
                    pool = None
                continue
            pool = None
    return None, None
