from tnc_tpu.parallel.sliced_parallel import (  # noqa: F401
    distributed_sliced_contraction,
    make_mesh,
)
