from tnc_tpu.parallel.partitioned import (  # noqa: F401
    Communication,
    DeviceTensorMapping,
    PartitionExecutionError,
    distributed_partitioned_contraction,
    intermediate_reduce,
    local_contract_partitions,
    scatter_partitions,
)
from tnc_tpu.parallel.sliced_parallel import (  # noqa: F401
    distributed_sliced_contraction,
    make_mesh,
)
