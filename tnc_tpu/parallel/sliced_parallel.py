"""Slice-parallel distributed contraction over a device mesh.

The reference parallelizes by graph partitioning + MPI fan-in
(``tnc/src/mpi/communication.rs``). On a TPU mesh, the natural first axis
of parallelism is different: **slices**. A sliced contraction is a sum of
``num_slices`` identical-shape programs — perfectly SPMD. Each device
executes its chunk of the slice range with the same compiled program and
a single ``psum`` over the mesh combines the partial sums on ICI.

This composes with partition parallelism (``tnc_tpu.parallel.partitioned``)
the way data parallelism composes with model parallelism in ML stacks.

Works on any ``jax.sharding.Mesh`` — real TPU ICI or the virtual CPU
device count used in tests (the ``mpi_test`` analogue).
"""

from __future__ import annotations

import logging
import numpy as np

logger = logging.getLogger(__name__)

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.slicing import Slicing
from tnc_tpu.ops.backends import _run_steps
from tnc_tpu.resilience import faultinject as _faults
from tnc_tpu.resilience import retry as _retry
from tnc_tpu.ops.program import flat_leaf_tensors
from tnc_tpu.ops.sliced import SlicedProgram, build_sliced_program
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


def _shard_map(f, mesh, in_specs, out_specs):
    """Replication-unchecked shard_map across jax versions: top-level
    ``jax.shard_map`` with ``check_vma`` on jax >= 0.8, the
    ``jax.experimental.shard_map`` spelling with ``check_rep`` on the
    0.4.x line (psum inside the body trips the strict checker either
    way)."""
    try:
        from jax import shard_map as sm

        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def _effective_chunk(
    num_slices: int, n_devices: int, max_slices: int | None
) -> int:
    """Per-device slice count actually executed: the full share, shrunk
    to ``ceil(max_slices / n_devices)`` under a probe subset. The ONE
    definition shared by the compiled fn, its cache key, and the trace
    flop accounting — they must never disagree on the chunk size."""
    chunk = num_slices // n_devices
    if max_slices is not None:
        chunk = min(chunk, max(1, -(-max_slices // n_devices)))
    return chunk


def make_mesh(n_devices: int | None = None, axis: str = "slices"):
    """Build a 1-D mesh over the first ``n_devices`` JAX devices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"({devices[0].platform})"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def _make_spmd_fn(
    sp: SlicedProgram,
    mesh,
    axis: str,
    dtype,
    split_complex: bool,
    precision: str | None = "float32",
    unroll: int = 1,
    max_slices: int | None = None,
    hoist: bool = False,
):
    """fn(full_buffers) replicated over the mesh; each device sums its
    slice chunk, then one psum over the mesh axis.

    ``unroll > 1`` runs each device's chunk as ``lax.scan(unroll=)``
    over its slice ids instead of a ``fori_loop`` — on real TPUs XLA
    pessimizes while-loop bodies ~150× (TPU_EVIDENCE_r03.md), and the
    unrolled scan presents straight-line step groups.

    ``max_slices`` probe subsets: each device's chunk shrinks to
    ``ceil(max_slices / n_devices)`` and device ``d`` covers slice ids
    ``[d*chunk, (d+1)*chunk)`` of the *shrunk* chunk — i.e. the probe
    is a partial sum over the **first** ``n_devices *
    ceil(max_slices/n_devices)`` slices globally (a contiguous prefix,
    directly comparable against oracle prefix sums), not a subset of
    each device's full-run range.

    ``hoist=True`` traces the slice-invariant prelude once per device
    before its slice loop (:mod:`tnc_tpu.ops.hoist`); the cached
    intermediates are loop constants in each device's HBM and only the
    residual program runs per slice."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_devices = mesh.shape[axis]
    num = sp.slicing.num_slices
    if num % n_devices != 0:
        raise ValueError(
            f"num_slices ({num}) must be divisible by mesh size ({n_devices})"
        )
    chunk = _effective_chunk(num, n_devices, max_slices)

    hp = None
    if hoist:
        from tnc_tpu.ops.hoist import hoist_sliced_program

        cand = hoist_sliced_program(sp)
        if not cand.is_noop:
            hp = cand
    loop_sp = hp.residual if hp is not None else sp

    dims = sp.slicing.dims
    part_dtype = "float64" if "128" in str(dtype) else "float32"

    def decompose(s):
        idx = []
        for d in reversed(dims):
            idx.append(s % d)
            s = s // d
        idx.reverse()
        return idx

    def index_buffer(arr, info, indices):
        view = arr
        offset = 0
        for ax, pos in info:
            view = jnp.take(view, indices[pos], axis=ax - offset)
            offset += 1
        return view

    if split_complex:
        from tnc_tpu.ops.split_complex import plan_kernels, run_steps_split

        loop_policy = plan_kernels(loop_sp.program)  # kernel ladder

        def one_slice(loop_buffers, s):
            indices = decompose(s)
            buffers = [
                (
                    index_buffer(re, info, indices),
                    index_buffer(im, info, indices),
                )
                for (re, im), info in zip(loop_buffers, loop_sp.slot_slices)
            ]
            return run_steps_split(
                jnp, loop_sp.program, buffers, precision, policy=loop_policy
            )

        def add(acc, contrib):
            return acc[0] + contrib[0], acc[1] + contrib[1]

        def zeros():
            return (
                jnp.zeros(sp.program.stored_result_shape, dtype=part_dtype),
                jnp.zeros(sp.program.stored_result_shape, dtype=part_dtype),
            )

    else:

        def one_slice(loop_buffers, s):
            indices = decompose(s)
            buffers = [
                index_buffer(arr, info, indices)
                for arr, info in zip(loop_buffers, loop_sp.slot_slices)
            ]
            return _run_steps(jnp, loop_sp.program, list(buffers))

        def add(acc, contrib):
            return acc + contrib

        def zeros():
            return jnp.zeros(sp.program.stored_result_shape, dtype=dtype)

    def device_fn(*full_buffers):
        my = lax.axis_index(axis)
        if hp is not None:
            # invariant prelude: traced once per device, outside the
            # slice loop — its outputs are loop constants in HBM
            from tnc_tpu.ops.hoist import run_prelude

            loop_buffers = run_prelude(
                jnp, hp, list(full_buffers), split_complex, precision
            )
        else:
            loop_buffers = full_buffers
        if unroll > 1:

            def body(acc, k):
                return add(acc, one_slice(loop_buffers, my * chunk + k)), None

            partial, _ = lax.scan(
                body, zeros(), jnp.arange(chunk), unroll=min(unroll, chunk)
            )
        else:

            def body(k, acc):
                return add(acc, one_slice(loop_buffers, my * chunk + k))

            partial = lax.fori_loop(0, chunk, body, zeros())
        return lax.psum(partial, axis)

    in_specs = tuple(P() for _ in range(sp.program.num_inputs))  # replicated
    fn = _shard_map(
        device_fn, mesh=mesh, in_specs=in_specs, out_specs=P()
    )
    return jax.jit(fn)


# Executable cache: _make_spmd_fn builds a fresh closure per call, so
# jax.jit alone can never dedupe — without this, a benchmark's timed
# call after a warmup at the SAME chunk would re-trace and re-compile
# inside the timed region (r5 review finding).
_SPMD_FN_CACHE: dict = {}
_SPMD_FN_CACHE_MAX = 64


def _spmd_fn_cached(sp, mesh, axis, dtype, split_complex, precision, unroll,
                    max_slices, hoist=False):
    from tnc_tpu.ops.split_complex import complex_mult_key, dot_precision_key

    n_devices = mesh.shape[axis]
    chunk = _effective_chunk(sp.slicing.num_slices, n_devices, max_slices)
    key = (
        sp.signature(), tuple(mesh.devices.flat), axis, str(dtype),
        split_complex, precision, unroll, chunk, hoist,
        # the split trace bakes in the kernel policy/env mode — a stale
        # fn under a flipped TNC_TPU_COMPLEX_MULT (or a flipped
        # TNC_TPU_DOT_PRECISION rung) would silently run the wrong
        # kernels
        complex_mult_key() if split_complex else None,
        dot_precision_key() if split_complex else None,
    )
    fn = _SPMD_FN_CACHE.get(key)
    obs.counter_add("spmd_fn_cache.hit" if fn is not None else
                    "spmd_fn_cache.miss")
    if fn is None:
        fn = _make_spmd_fn(
            sp, mesh, axis, dtype, split_complex, precision, unroll,
            max_slices, hoist,
        )
        _SPMD_FN_CACHE[key] = fn
        while len(_SPMD_FN_CACHE) > _SPMD_FN_CACHE_MAX:
            _SPMD_FN_CACHE.pop(next(iter(_SPMD_FN_CACHE)))
    return fn


def distributed_sliced_contraction(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    slicing: Slicing,
    mesh=None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    axis: str = "slices",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    unroll: int = 1,
    max_slices: int | None = None,
    hoist: bool = False,
) -> LeafTensor:
    """Contract ``tn`` with slices distributed over a device mesh.

    ``max_slices``: probe subsets — the partial sum over the **first**
    ``n_devices * ceil(max_slices / n_devices)`` slices globally (each
    device covers a contiguous range of that prefix; see
    :func:`_make_spmd_fn`).

    ``hoist=True``: each device computes the slice-invariant prelude
    once before its slice loop and iterates only the residual program
    (:mod:`tnc_tpu.ops.hoist`).

    Every device holds the (replicated, small) leaf tensors, runs the same
    compiled per-slice program over its chunk of the slice range, and the
    partial sums reduce with one ``psum`` on ICI. Split-complex mode is
    selected automatically off-CPU (the TPU runtime has no complex
    dtypes).

    >>> import numpy as np
    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> from tnc_tpu.contractionpath.slicing import find_slicing
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> rng = np.random.default_rng(0)
    >>> ts = [LeafTensor([0, 1], [4, 4], TensorData.matrix(rng.standard_normal((4, 4)))),
    ...       LeafTensor([1, 2], [4, 4], TensorData.matrix(rng.standard_normal((4, 4)))),
    ...       LeafTensor([2, 0], [4, 4], TensorData.matrix(rng.standard_normal((4, 4))))]
    >>> tn = CompositeTensor([t.copy() for t in ts])
    >>> path = ContractionPath.simple([(0, 1), (0, 2)])
    >>> slicing = find_slicing(ts, path.toplevel, target_size=12)
    >>> out = distributed_sliced_contraction(tn, path, slicing, n_devices=1)
    >>> a, b, c = (t.data.into_data() for t in ts)
    >>> want = np.einsum("ab,bc,ca->", a, b, c)
    >>> bool(abs(complex(out.data.into_data().reshape(-1)[0]) - want)
    ...      <= 1e-5 * abs(want))
    True
    """
    import jax
    import jax.numpy as jnp

    if mesh is None:
        mesh = make_mesh(n_devices, axis)
    if split_complex is None:
        split_complex = jax.devices()[0].platform != "cpu"

    sp = build_sliced_program(tn, contract_path, slicing)
    leaves = flat_leaf_tensors(tn)
    logger.debug(
        "sliced SPMD: %d slices over %d devices (%d sliced legs, "
        "split_complex=%s)",
        slicing.num_slices,
        mesh.shape[axis],
        len(slicing.legs),
        split_complex,
    )
    fn = _spmd_fn_cached(
        sp, mesh, axis, dtype, split_complex, precision, unroll, max_slices,
        hoist,
    )
    n_dev = mesh.shape[axis]
    chunk = _effective_chunk(slicing.num_slices, n_dev, max_slices)
    executed = chunk * n_dev  # prefix-subset semantics (_make_spmd_fn)
    # the SAME effective-hoist decision _make_spmd_fn takes (the pass is
    # lru-cached, so this re-derivation is a dict hit), so the span's
    # hoisted flag and flop count describe what actually executes
    hp = None
    if hoist:
        from tnc_tpu.ops.hoist import hoist_sliced_program

        cand = hoist_sliced_program(sp)
        if not cand.is_noop:
            hp = cand
    # device-level profiling (TNC_TPU_TRACE_JAX=<dir>) wraps the SPMD
    # dispatch + fetch; obs spans record the host-side wall time either way
    with obs.maybe_jax_profiler_trace(), obs.span(
        "spmd.contract",
        slices=executed,
        devices=n_dev,
        hoisted=hp is not None,
    ) as osp:
        # transient runtime failures (preemption notice on one chip, ICI
        # hiccup) retry the whole SPMD dispatch under the shared policy —
        # the computation is replicated-input + psum, so a re-dispatch is
        # exact; OOM propagates to the caller's degradation ladder
        if split_complex:
            from tnc_tpu.ops.split_complex import combine_array, split_array

            part_dtype = "float64" if "128" in str(dtype) else "float32"
            arrays = []
            for leaf in leaves:
                re, im = split_array(leaf.data.into_data(), part_dtype)
                arrays.append((jnp.asarray(re), jnp.asarray(im)))
        else:
            arrays = [
                jnp.asarray(leaf.data.into_data(), dtype=dtype)
                for leaf in leaves
            ]

        def _dispatch():
            _faults.fault_point("spmd.dispatch")
            out = fn(*arrays)
            if _retry.sync_dispatch():
                jax.block_until_ready(out)
            return out

        if split_complex:
            re, im = _retry.retry_call(_dispatch, label="spmd.dispatch")
            result = combine_array(re, im).reshape(sp.program.result_shape)
        else:
            result = np.asarray(
                _retry.retry_call(_dispatch, label="spmd.dispatch")
            ).reshape(sp.program.result_shape)
        if obs.enabled():
            from tnc_tpu.ops.program import steps_flops

            if hp is not None:
                # hoisted: each device runs the prelude once, then the
                # residual per slice of its chunk
                flops = n_dev * steps_flops(
                    ps.step for ps in hp.prelude_steps
                ) + executed * steps_flops(hp.residual.program.steps)
            else:
                flops = executed * steps_flops(sp.program.steps)
            osp.add(flops=flops)
    return LeafTensor(
        list(sp.program.result_legs),
        list(sp.program.result_shape),
        TensorData.matrix(result),
    )
