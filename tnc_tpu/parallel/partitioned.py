"""Partition-parallel distributed contraction over JAX devices.

TPU-native equivalent of the reference's MPI runtime
(``tnc/src/mpi/communication.rs``). The reference's pipeline is

    rank 0: partition → per-partition paths → toplevel fan-in path
    broadcast_path / scatter_tensor_network    (bcast + p2p sends)
    every rank: contract its partition locally (zero communication)
    intermediate_reduce_tensor_network         (pairwise p2p fan-in)

Here the same schedule runs under JAX's single-controller model:

- *Scatter* = ``jax.device_put`` of each partition's leaf tensors onto its
  device. No serialization layer is needed (the reference needs postcard +
  192-byte MPI blobs, ``mpi/serialization.rs``, ``mpi_types.rs:73-83``);
  arrays move host→HBM directly.
- *Local phase* = each partition's whole nested path compiled to one XLA
  program and dispatched to its device. JAX dispatch is asynchronous, so
  all devices compute their partitions **concurrently** — the analogue of
  the independent per-rank contraction phase.
- *Fan-in reduce* = the ``toplevel`` path interpreted as a communication
  schedule, exactly like ``intermediate_reduce_tensor_network``
  (``communication.rs:199-249``): for each pair ``(x, y)`` the tensor held
  by ``y``'s device is ``device_put`` onto ``x``'s device (a direct
  device-to-device copy — ICI on a TPU slice) and contracted there.
- *Final tensor on device 0*: ``DeviceTensorMapping`` assigns the
  partition that survives the fan-in to device 0, mirroring
  ``get_tensor_mapping`` reserving rank 0 (``communication.rs:89-115``).

Multi-host scaling: under ``jax.distributed.initialize`` the same code
addresses every device in the pod; ``device_put`` between hosts rides
DCN. There is no rank-local control flow to port.
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

logger = logging.getLogger(__name__)

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.ops.backends import jit_program, place_buffers
from tnc_tpu.ops.program import (
    ContractionProgram,
    _pair_step,
    build_program,
)
from tnc_tpu.resilience import faultinject as _faults
from tnc_tpu.resilience import retry as _retry
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


def _process_index() -> int:
    """This host's jax process index (0 when jax is not initialized —
    error paths must not fail while naming a failure)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — never raise from error naming
        return 0


class PartitionExecutionError(RuntimeError):
    """A partition's scatter, local contraction, or fan-in step failed;
    names the partition index, device slot, and **host process** so a
    pool-surfaced XLA error in a multi-host incident log is attributable
    to a machine (``pool.map`` otherwise raises a bare runtime error
    with no hint of which partition — let alone which host — died).
    Chains the original (``__cause__``)."""

    def __init__(
        self,
        partition: int,
        device: int,
        original: BaseException,
        process: int | None = None,
        phase: str = "local",
    ):
        if process is None:
            process = _process_index()
        super().__init__(
            f"partition {partition} on device {device} "
            f"(process {process}, {phase} phase) failed: "
            f"{type(original).__name__}: {original}"
        )
        self.partition = partition
        self.device = device
        self.process = process
        self.phase = phase
        self.original = original

def partition_latency_map(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    cost_model=None,
) -> dict[int, float]:
    """Per-partition local completion latencies for fan-in scheduling —
    never ``None``-filled: predicted seconds under ``cost_model`` (a
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`, dispatch
    overhead charged per local step), raw local op counts otherwise.

    This is what the latency-aware communication schemes
    (``WEIGHTED_BRANCH_BOUND``, ``BIPARTITION_SWEEP``) must receive on
    the partitioned path: with an empty latency map every partition
    looks instantly available and the "latency-aware" schedule
    degenerates to a plain flops fan-in.
    """
    from tnc_tpu.contractionpath.contraction_cost import contract_path_cost

    latency: dict[int, float] = {}
    steps: dict[int, float] = {}
    for i, child in enumerate(tn.tensors):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {i} is not a partition composite")
        if i not in contract_path.nested:
            raise ValueError(f"partition {i} has no nested contraction path")
        local = contract_path.nested[i]
        flops, _ = contract_path_cost(child.tensors, local, True)
        latency[i] = flops
        steps[i] = float(len(local.toplevel))
    if cost_model is not None:
        from tnc_tpu.contractionpath.communication_schemes import (
            calibrated_latency_map,
        )

        latency = calibrated_latency_map(latency, cost_model, steps)
    return latency


def replan_fanin(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    communication_scheme,
    cost_model=None,
    rng=None,
) -> ContractionPath:
    """Re-derive the toplevel fan-in schedule of a partitioned path with
    a latency-aware communication scheme, keeping the nested local
    paths. The latency map comes from :func:`partition_latency_map` —
    calibrated seconds when a ``cost_model`` is given — so deferring a
    slow partition's tensor is priced against real completion times.
    """
    import random as _random

    latency = partition_latency_map(tn, contract_path, cost_model)
    children = [
        child.external_tensor() for child in tn.tensors
    ]  # type: ignore[union-attr]
    toplevel = communication_scheme.communication_path(
        children,
        latency,
        rng if rng is not None else _random.Random(42),
        cost_model=cost_model,
    )
    return ContractionPath(dict(contract_path.nested), list(toplevel))


def _fanin_survivor(k: int, toplevel: Sequence[tuple[int, int]]) -> int:
    """Index that holds the final tensor after a replace-left fan-in."""
    alive = [True] * k
    for x, y in toplevel:
        if not (alive[x] and alive[y]):
            raise ValueError(f"communication path reuses a consumed index: {(x, y)}")
        alive[y] = False
    survivors = [i for i, a in enumerate(alive) if a]
    if len(survivors) != 1:
        raise ValueError(
            f"communication path leaves {len(survivors)} tensors, expected 1"
        )
    return survivors[0]


@dataclass(frozen=True)
class DeviceTensorMapping:
    """Partition index ↔ device, final-result partition pinned to device 0.

    Equivalent of ``RankTensorMapping`` (``mpi/mpi_types.rs:11-62``) +
    ``get_tensor_mapping`` (``communication.rs:89-115``).
    """

    device_of_partition: tuple[int, ...]  # partition i → device slot

    @classmethod
    def for_path(
        cls, k: int, toplevel: Sequence[tuple[int, int]]
    ) -> "DeviceTensorMapping":
        root = _fanin_survivor(k, toplevel)
        order = [root] + [i for i in range(k) if i != root]
        device_of = [0] * k
        for slot, part in enumerate(order):
            device_of[part] = slot
        return cls(tuple(device_of))

    def device(self, partition: int) -> int:
        return self.device_of_partition[partition]


@dataclass
class Communication:
    """Executor state for one distributed contraction (cf. ``Communication``
    in ``communication.rs:118-122``).

    ``programs[i]`` is either a :class:`ContractionProgram` (partition
    fits HBM) or a :class:`~tnc_tpu.ops.sliced.SlicedProgram` (partition
    sliced to fit — the slicing × partitioning composition the reference
    lists as future work, ``book/src/future_work.md`` item 2)."""

    mapping: DeviceTensorMapping
    devices: list
    programs: list[Any]
    results_meta: list[LeafTensor]


def _pair_program(ta: LeafTensor, tb: LeafTensor) -> tuple[ContractionProgram, LeafTensor]:
    step, result = _pair_step(0, 1, ta, tb)
    program = ContractionProgram(
        num_inputs=2,
        steps=(step,),
        result_slot=0,
        result_legs=tuple(result.legs),
        result_shape=tuple(result.bond_dims),
    )
    return program, result


def _leaf_arrays(child: CompositeTensor) -> list[np.ndarray]:
    from tnc_tpu.ops.program import flat_leaf_tensors

    return [np.asarray(leaf.data.into_data()) for leaf in flat_leaf_tensors(child)]


def _slice_partition(child: CompositeTensor, nested: ContractionPath, hbm_bytes: int):
    """Slice one partition's local path until its program fits the HBM
    budget. Returns a SlicedProgram (or None if the unsliced program
    already fits, or nothing local slicing can do).

    Uses slice-and-reconfigure (slicing interleaved with subtree
    re-planning in the sliced size model) rather than plain greedy leg
    picking: a fixed path's peak is often pinned by a single badly-
    ordered step that reconfiguration dissolves once the sliced legs
    have dim 1. The returned ``SlicedProgram``'s program may therefore
    follow a DIFFERENT (better) local path than ``nested`` — downstream
    fan-in metadata must come from ``sp.program.result_legs`` (it does:
    ``scatter_partitions`` builds metas from the program).
    """
    from tnc_tpu.contractionpath.contraction_path import replace_ssa_ordering
    from tnc_tpu.contractionpath.slicing import slice_and_reconfigure
    from tnc_tpu.ops.budget import fits_hbm, program_peak_bytes
    from tnc_tpu.ops.sliced import build_sliced_program

    program = build_program(child, nested)
    if fits_hbm(program, hbm_bytes=hbm_bytes):
        return None
    if nested.nested:
        raise ValueError(
            "HBM budget exceeded on a partition with a nested local path; "
            "slicing supports flat partition paths"
        )
    inputs = [t for t in child.tensors if isinstance(t, LeafTensor)]
    est = program_peak_bytes(program)
    ssa = replace_ssa_ordering(nested.toplevel, len(inputs))
    # element targets, descending from a quarter of the current peak
    # (~8 bytes per complex element; starting AT the peak would be a
    # no-op): first slicing that fits the budget wins; keep the deepest
    # achievable as best effort. A partition whose peak is its own
    # open-leg output cannot be sliced locally at all — only GLOBAL
    # slicing (cut legs sliceable) helps there.
    target = 2.0 ** np.floor(np.log2(max(est.peak_bytes / 8.0 / 4.0, 2.0)))
    best = None
    while target >= 4:
        try:
            pairs, slicing = slice_and_reconfigure(
                inputs, ssa, target,
                reconf_rounds=1, step_budget=None,
                final_rounds=2, final_budget=None,
            )
        except ValueError:
            break
        if not slicing.legs:  # target above the current peak: no-op
            target /= 4.0
            continue
        sp = build_sliced_program(child, ContractionPath.simple(pairs), slicing)
        best = sp
        if fits_hbm(sp.program, hbm_bytes=hbm_bytes):
            break
        target /= 4.0
    if best is None:
        # nothing sliceable (open-leg-bound peak): run unsliced rather
        # than wrap a fake 1-slice program as success
        logger.warning(
            "partition peak %.3g bytes exceeds the %d-byte budget but has "
            "no sliceable (closed) legs; running unsliced — use global "
            "slicing (partitioned_sliced_executor) to slice cut legs",
            est.peak_bytes,
            hbm_bytes,
        )
        return None
    if not fits_hbm(best.program, hbm_bytes=hbm_bytes):
        logger.warning(
            "partition sliced best-effort (%d legs, %d slices) but still "
            "exceeds the %d-byte budget",
            len(best.slicing.legs),
            best.slicing.num_slices,
            hbm_bytes,
        )
    logger.debug(
        "partition sliced: %d legs, %d slices",
        len(best.slicing.legs),
        best.slicing.num_slices,
    )
    return best


def scatter_partitions(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list,
    dtype: str,
    split_complex: bool,
    hbm_bytes: int | None = None,
) -> tuple[Communication, list[list[Any]]]:
    """Compile per-partition programs and place each partition's leaves on
    its device (``scatter_tensor_network``, ``communication.rs:125-195``).

    With ``hbm_bytes`` set, any partition whose program exceeds the
    per-device budget is sliced locally (sum over slice programs on its
    own device) before the fan-in — composing partition parallelism with
    slicing.
    """
    children = list(tn.tensors)
    k = len(children)
    for i, child in enumerate(children):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {i} is not a partition composite")
        if i not in contract_path.nested:
            raise ValueError(f"partition {i} has no nested contraction path")
    if k > len(devices):
        raise ValueError(f"{k} partitions but only {len(devices)} devices")

    mapping = DeviceTensorMapping.for_path(k, contract_path.toplevel)

    programs: list[Any] = []
    metas: list[LeafTensor] = []
    buffers: list[list[Any]] = []
    with obs.span("partitioned.scatter", partitions=k):
        for i, child in enumerate(children):
            try:
                sp = None
                if hbm_bytes is not None:
                    sp = _slice_partition(
                        child, contract_path.nested[i], hbm_bytes
                    )
                if sp is not None:
                    programs.append(sp)
                    program = sp.program
                else:
                    program = build_program(child, contract_path.nested[i])
                    programs.append(program)
                metas.append(
                    LeafTensor(
                        list(program.result_legs), list(program.result_shape)
                    )
                )
                buffers.append(
                    place_buffers(
                        _leaf_arrays(child), dtype, split_complex,
                        devices[mapping.device(i)],
                    )
                )
            except (ValueError, TypeError):
                raise  # caller contract errors keep their type
            except Exception as exc:  # noqa: BLE001 — name the failure site
                raise PartitionExecutionError(
                    i, mapping.device(i), exc, phase="scatter"
                ) from exc
            # mirror of "Scattering tensor network" (communication.rs:132)
            logger.debug(
                "scatter: partition %d -> device %d (%d tensors, %d steps%s)",
                i,
                mapping.device(i),
                len(child),
                len(program.steps),
                ", sliced" if sp is not None else "",
            )

    comm = Communication(mapping, list(devices), programs, metas)
    return comm, buffers


def local_contract_partitions(
    comm: Communication,
    buffers: list[list[Any]],
    split_complex: bool,
    precision,
    max_slices: int | None = None,
    sliced_strategy: str = "chunked",
    dtype: str = "complex64",
    slice_batch: int = 8,
    chunk_steps: int = 64,
    hoist: bool = False,
) -> list[Any]:
    """Dispatch every partition's compiled program to its device. Async
    dispatch → all devices run concurrently (the per-rank local phase).
    ``max_slices`` caps sliced partitions' loops (benchmark subset mode —
    the partial sums are NOT the correct partition tensors).
    ``hoist=True`` runs each sliced partition's slice-invariant stem
    once before its slice loop (:mod:`tnc_tpu.ops.hoist`).

    Sliced partitions run through the chunked executor by default (the
    on-device ``fori_loop`` is ~150× slower on real TPUs,
    TPU_EVIDENCE_r03.md); each partition's buffers are committed to its
    device, so the per-partition chunk dispatches execute there and the
    k local phases still overlap. ``sliced_strategy="loop"`` keeps the
    single-dispatch loop program (fewer host round-trips — the virtual
    CPU mesh doesn't pessimize loop bodies).

    First-run XLA compiles are driven from a thread pool: k distinct
    partition programs would otherwise compile back-to-back on the main
    thread (XLA compilation releases the GIL), serializing exactly the
    phase that should overlap. Warm runs take the sequential fast path.

    ``sliced_strategy="mesh"``: a locally sliced partition's slice
    partial sums reduce with an **on-device collective** (``psum`` over
    a sub-mesh axis) instead of the chunked executor's host
    accumulation loop — partials stay device-resident end to end, and
    devices beyond the partition count (``comm.devices[k:]``) are
    farmed out to the sliced partitions, each of which runs its slice
    range SPMD over its sub-mesh (``tnc_tpu.parallel.sliced_parallel``
    machinery; the sub-mesh shrinks to the largest size dividing the
    partition's slice count).
    """
    if sliced_strategy not in ("chunked", "loop", "mesh"):
        raise ValueError(
            f"unknown sliced_strategy {sliced_strategy!r}; "
            "expected 'chunked', 'loop', or 'mesh'"
        )
    logger.debug("local phase: %d partition programs", len(comm.programs))
    from tnc_tpu.ops.chunked import run_sliced_chunked_placed
    from tnc_tpu.ops.sliced import SlicedProgram, make_jax_sliced_fn

    # mesh strategy: hand the spare devices (slots beyond the partition
    # count) to the sliced partitions, round-robin
    spare_of: dict[int, list] = {}
    if sliced_strategy == "mesh":
        k = len(comm.programs)
        sliced_parts = [
            i for i, p in enumerate(comm.programs)
            if isinstance(p, SlicedProgram)
        ]
        spare_of = {i: [] for i in sliced_parts}
        for j, dev in enumerate(comm.devices[k:]):
            if sliced_parts:
                spare_of[sliced_parts[j % len(sliced_parts)]].append(dev)

    def _mesh_fn(i, program):
        import numpy as _np
        from jax.sharding import Mesh

        from tnc_tpu.parallel.sliced_parallel import _spmd_fn_cached

        own = comm.devices[comm.mapping.device(i)]
        sub = [own] + spare_of.get(i, [])
        n = len(sub)
        while program.slicing.num_slices % n:
            n -= 1
        submesh = Mesh(_np.asarray(sub[:n]), ("slices",))
        fn = _spmd_fn_cached(
            program, submesh, "slices", dtype, split_complex, precision,
            1, max_slices, hoist,
        )

        def run(bufs, _fn=fn, _own=own):
            import jax

            # the SPMD fn replicates its inputs over the sub-mesh
            # itself; feed host copies so single-device-committed
            # buffers never fight the mesh sharding
            host = [
                (np.asarray(b[0]), np.asarray(b[1]))
                if isinstance(b, tuple)
                else np.asarray(b)
                for b in bufs
            ]
            out = _fn(*host)
            # psum leaves the (replicated) sum on the sub-mesh; the
            # fan-in contracts single-device buffers, so land the
            # partition's copy back on its own device (free when the
            # sub-mesh is just that device)
            return jax.device_put(out, _own)

        return run

    def compile_one(i, program):
        if isinstance(program, SlicedProgram):
            if sliced_strategy == "mesh":
                return _mesh_fn(i, program)
            if sliced_strategy == "chunked":
                dev = comm.devices[comm.mapping.device(i)]

                def run(bufs, _sp=program, _dev=dev):
                    return run_sliced_chunked_placed(
                        _sp,
                        bufs,
                        batch=slice_batch,
                        chunk_steps=chunk_steps,
                        split_complex=split_complex,
                        precision=precision,
                        dtype=dtype,
                        device=_dev,
                        max_slices=max_slices,
                        hoist=hoist,
                    )

                return run
            return make_jax_sliced_fn(
                program,
                split_complex=split_complex,
                precision=precision,
                num_slices=max_slices,
                hoist=hoist,
            )
        return jit_program(program, split_complex, precision)

    def run_job(i, fn, bufs):
        # runs on the pool worker thread, so each partition's span lands
        # on its own timeline lane (tid) in the exported trace
        dev = comm.mapping.device(i)
        with obs.span(
            "partitioned.local_partition",
            partition=i,
            device=dev,
        ):
            # transient failures retry THIS partition in place (bounded,
            # shared policy) instead of killing the pool with the other
            # partitions' finished work; anything that survives the
            # retries is re-raised naming the partition and device
            def _attempt():
                _faults.fault_point("partition.local", partition=i, device=dev)
                return fn(bufs)

            try:
                # unsliced partition programs dispatch with donated
                # inputs (jit_program default), so the donation guard
                # blocks retries once a failed dispatch consumed them
                return _retry.default_policy().run(
                    _attempt,
                    label="partition.local",
                    classify=_retry.donation_guarded_classify(bufs),
                )
            except Exception as exc:  # noqa: BLE001 — annotate and re-raise
                raise PartitionExecutionError(i, dev, exc) from exc

    jobs = [
        (i, compile_one(i, program), list(bufs))
        for i, (program, bufs) in enumerate(zip(comm.programs, buffers))
    ]
    with obs.span("partitioned.local", partitions=len(jobs)):
        if len(jobs) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
                return list(pool.map(lambda job: run_job(*job), jobs))
        return [run_job(i, fn, bufs) for i, fn, bufs in jobs]


def _buffer_nbytes(buf: Any) -> float:
    """Bytes a held fan-in buffer occupies on device (a (real, imag)
    pair in split mode; best-effort 0.0 when the array type hides it)."""
    try:
        if isinstance(buf, tuple):
            return float(sum(_buffer_nbytes(p) for p in buf))
        return float(buf.size) * float(buf.dtype.itemsize)
    except Exception:  # noqa: BLE001 — accounting must never fail a run
        return 0.0


def plan_fanin_pairs(
    metas: Sequence[LeafTensor], toplevel: Sequence[tuple[int, int]]
) -> tuple[list[ContractionProgram], list[LeafTensor], list[float], LeafTensor]:
    """Precompute the whole fan-in schedule's pair programs: for each
    pair ``(x, y)`` of the communication path, its 2-tensor program, the
    meta of the tensor **moved** (y's, the ICI/DCN payload), and the
    pair's flop count. Returns ``(programs, moved_metas, flops,
    final_meta)``. Hoisting this out of the reduce loop keeps the
    per-level hot path free of planning work — a level's dispatches go
    back-to-back with no host-side program construction between them."""
    from tnc_tpu.ops.program import step_flops

    pair_meta = list(metas)
    programs: list[ContractionProgram] = []
    moved: list[LeafTensor] = []
    flops: list[float] = []
    for x, y in toplevel:
        program, result_meta = _pair_program(pair_meta[x], pair_meta[y])
        programs.append(program)
        moved.append(pair_meta[y])
        flops.append(float(step_flops(program.steps[0])))
        pair_meta[x] = result_meta
    root = _fanin_survivor(len(metas), toplevel) if toplevel else 0
    return programs, moved, flops, pair_meta[root]


def intermediate_reduce(
    comm: Communication,
    toplevel: Sequence[tuple[int, int]],
    results: list[Any],
    split_complex: bool,
    precision,
    levels: Sequence[Sequence[tuple[int, int]]] | None = None,
) -> tuple[Any, LeafTensor]:
    """Overlapped tree fan-in following the communication path
    (``intermediate_reduce_tensor_network``, ``communication.rs:199-249``):
    for ``(x, y)``, move y's tensor onto x's device and contract there.

    The path is grouped into dependency **levels**
    (:func:`~tnc_tpu.contractionpath.communication_schemes.fanin_levels`
    — derived from the communication scheme's own pair order, so a
    latency-aware schedule priced with the calibrated latency map keeps
    its tree shape). All pairs of a level are independent by
    construction and dispatch back-to-back with **no intervening host
    synchronization** — jax dispatch is asynchronous, so a level's
    device-to-device moves and pair contractions all run concurrently;
    partials stay device-resident between levels (nothing returns to
    the host until the survivor is fetched by the caller). One
    ``partitioned.fanin_level`` span per level records the pair count,
    bytes moved over the interconnect, and pair flops — the reduce
    phase's roofline input (``trace_summarize.py --roofline``).
    """
    import jax

    metas = list(comm.results_meta)
    held: list[Any] = list(results)
    if levels is None:
        from tnc_tpu.contractionpath.communication_schemes import fanin_levels

        levels = fanin_levels(toplevel)
    # program bookkeeping in FLATTENED level order (a caller-supplied
    # level schedule may reorder independent pairs relative to the
    # path; the tree — which tensors meet — is unchanged either way)
    flat = [pair for level in levels for pair in level]
    programs, moved_metas, pair_flops, final_meta = plan_fanin_pairs(
        metas, flat
    )
    proc = _process_index()
    with obs.span(
        "partitioned.fanin", pairs=len(flat), levels=len(levels)
    ) as fanin_sp:
        total_bytes = 0.0
        total_flops = 0.0
        pi = 0
        for li, level in enumerate(levels):
            with obs.span(
                "partitioned.fanin_level", level=li, pairs=len(level)
            ) as level_sp:
                level_bytes = 0.0
                level_flops = 0.0
                for x, y in level:
                    dev = comm.mapping.device(x)
                    target = comm.devices[dev]
                    logger.debug(
                        "fan-in L%d: partition %d (device %d) <- "
                        "partition %d (device %d)",
                        li, x, dev, y, comm.mapping.device(y),
                    )
                    try:
                        # async: device_put and the pair dispatch both
                        # return immediately; the level's pairs overlap
                        # on their devices while the host loops on
                        moved = jax.device_put(held[y], target)
                        fn = jit_program(programs[pi], split_complex, precision)
                        out = fn([held[x], moved])
                    except Exception as exc:  # noqa: BLE001 — name the site
                        raise PartitionExecutionError(
                            x, dev, exc, process=proc, phase="fanin"
                        ) from exc
                    level_bytes += _buffer_nbytes(held[y])
                    level_flops += pair_flops[pi]
                    held[x] = out
                    held[y] = None
                    pi += 1
                if obs.enabled():
                    level_sp.add(bytes=level_bytes, flops=level_flops)
                total_bytes += level_bytes
                total_flops += level_flops
        if obs.enabled():
            fanin_sp.add(bytes=total_bytes, flops=total_flops)
    root = _fanin_survivor(len(held), flat) if flat else 0
    return held[root], final_meta if flat else comm.results_meta[root]


def process_shard_map(
    k: int, toplevel: Sequence[tuple[int, int]], n_procs: int
) -> tuple[int, ...]:
    """Partition index → owning host process for the process-sharded
    executor. The fan-in survivor is pinned to process 0 (the reference's
    rank-0 contract); the rest round-robin across processes so every
    host carries a near-equal share of the local phase.

    >>> process_shard_map(4, [(0, 1), (2, 3), (0, 2)], 2)
    (0, 1, 0, 1)
    """
    root = _fanin_survivor(k, toplevel) if toplevel else 0
    n_procs = max(int(n_procs), 1)
    owner = [0] * k
    for j, part in enumerate(i for i in range(k) if i != root):
        owner[part] = (j + 1) % n_procs
    return tuple(owner)


def _fetch_host(buf: Any):
    """Device buffer → host numpy payload for the KV transport (a
    (real, imag) numpy pair in split mode)."""
    if isinstance(buf, tuple):
        return tuple(np.asarray(p) for p in buf)
    return np.asarray(buf)


def _process_sharded_contraction(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    dtype: str,
    split_complex: bool | None,
    precision,
    hbm_bytes: int | None,
    local_sliced_strategy: str,
    slice_batch: int,
    chunk_steps: int,
    hoist: bool,
) -> LeafTensor:
    """Multi-host partitioned contraction under
    ``jax.distributed.initialize``: partitions shard across processes
    (:func:`process_shard_map`), each host scatters its partitions onto
    its **local** devices and contracts them concurrently, and the
    fan-in walks the level schedule in process-spanning order — a pair
    whose operands live on one host reduces device-to-device there; a
    cross-host pair ships y's tensor over the coordination-KV
    :func:`broadcast_object` transport (the channel PR 7 hardened
    against the silent-zeros gloo collective) to x's owner, which
    contracts on device. Every process walks the same schedule, so the
    collectives stay in lockstep by construction; the final tensor is
    broadcast from the survivor's owner (process 0) to all processes,
    and the result is **bit-identical** to the single-host executor
    (same pair programs, same per-pair arithmetic, byte-exact
    transport).
    """
    import jax

    n_procs = jax.process_count()
    me = jax.process_index()
    local_devices = jax.local_devices()
    if split_complex is None:
        split_complex = local_devices[0].platform != "cpu"

    children = list(tn.tensors)
    k = len(children)
    for i, child in enumerate(children):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {i} is not a partition composite")
        if i not in contract_path.nested:
            raise ValueError(f"partition {i} has no nested contraction path")
    owner = process_shard_map(k, contract_path.toplevel, n_procs)
    mine = [i for i in range(k) if owner[i] == me]

    # every process derives ALL partition programs host-side (cheap, no
    # communication): pair programs and result metas must agree
    # everywhere for the schedule to stay in lockstep
    programs: list[Any] = []
    metas: list[LeafTensor] = []
    with obs.span(
        "partitioned.scatter", partitions=len(mine), process=me
    ):
        for i, child in enumerate(children):
            sp = None
            if hbm_bytes is not None:
                sp = _slice_partition(child, contract_path.nested[i], hbm_bytes)
            if sp is not None:
                programs.append(sp)
                program = sp.program
            else:
                program = build_program(child, contract_path.nested[i])
                programs.append(program)
            metas.append(
                LeafTensor(
                    list(program.result_legs), list(program.result_shape)
                )
            )
        # buffers land only on the owner's local devices
        dev_slot = {
            part: idx % len(local_devices) for idx, part in enumerate(mine)
        }
        buffers = {}
        for i in mine:
            try:
                buffers[i] = place_buffers(
                    _leaf_arrays(children[i]), dtype, split_complex,
                    local_devices[dev_slot[i]],
                )
            except Exception as exc:  # noqa: BLE001 — name the site
                raise PartitionExecutionError(
                    i, dev_slot[i], exc, process=me, phase="scatter"
                ) from exc

    # local phase: this host's partitions only, overlapped via the
    # shared thread-pool dispatch path
    sub = Communication(
        DeviceTensorMapping(tuple(dev_slot[i] for i in mine)),
        list(local_devices),
        [programs[i] for i in mine],
        [metas[i] for i in mine],
    )
    try:
        results = local_contract_partitions(
            sub,
            [buffers[i] for i in mine],
            split_complex,
            precision,
            sliced_strategy=local_sliced_strategy,
            dtype=dtype,
            slice_batch=slice_batch,
            chunk_steps=chunk_steps,
            hoist=hoist,
        )
    except PartitionExecutionError as exc:
        # remap the sub-communication's local index to the global
        # partition id so multi-host incident logs name the real site
        raise PartitionExecutionError(
            mine[exc.partition], exc.device, exc.original,
            process=me, phase=exc.phase,
        ) from exc.original
    held: dict[int, Any] = dict(zip(mine, results))

    from tnc_tpu.contractionpath.communication_schemes import fanin_levels

    levels = fanin_levels(contract_path.toplevel)
    flat = [pair for level in levels for pair in level]
    pair_programs, moved_metas, pair_flops, final_meta = plan_fanin_pairs(
        metas, flat
    )
    item_bytes = float(np.dtype(dtype).itemsize)
    # one p2p namespace per fan-in: cross-host pairs move point-to-point
    # (sender publishes, x's owner reads; uninvolved hosts skip the
    # transfer entirely) instead of an all-process broadcast per pair.
    # Every process reserves it — counter alignment — even if no pair
    # of the schedule crosses hosts.
    p2p_seq = p2p_sequence()
    pi = 0
    with obs.span(
        "partitioned.fanin",
        pairs=len(flat), levels=len(levels), process=me,
    ) as fanin_sp:
        total_bytes = 0.0
        total_flops = 0.0
        cross = 0
        for li, level in enumerate(levels):
            with obs.span(
                "partitioned.fanin_level",
                level=li, pairs=len(level), process=me,
            ) as level_sp:
                level_bytes = 0.0
                level_flops = 0.0
                for x, y in level:
                    ox, oy = owner[x], owner[y]
                    moved = None
                    # every moved pair counts the payload (same meta
                    # bytes whether it rides ICI on one host or DCN
                    # across hosts) — keeps interconnect_bytes
                    # comparable with the single-host executor's
                    pair_bytes = (
                        float(np.prod(moved_metas[pi].bond_dims))
                        * item_bytes
                    )
                    if ox == oy:
                        if ox == me:
                            target = local_devices[dev_slot[x]]
                            moved = jax.device_put(held.pop(y), target)
                        level_bytes += pair_bytes
                    else:
                        # cross-host pair: y's owner publishes, x's
                        # owner reads — point-to-point, O(payload) on
                        # the wire; hosts owning neither side never
                        # block on (or unpickle) this tensor
                        cross += 1
                        if p2p_seq is not None:
                            if oy == me:
                                send_object(
                                    _fetch_host(held.pop(y)), p2p_seq, pi
                                )
                            elif ox == me:
                                target = local_devices[dev_slot[x]]
                                moved = jax.device_put(
                                    recv_object(p2p_seq, pi), target
                                )
                        else:
                            # no coordination client: all-process
                            # broadcast fallback (lockstep per pair)
                            payload = (
                                _fetch_host(held.pop(y)) if oy == me else None
                            )
                            obj = broadcast_object(payload, root=oy)
                            if ox == me:
                                target = local_devices[dev_slot[x]]
                                moved = jax.device_put(obj, target)
                        level_bytes += pair_bytes
                    if ox == me:
                        try:
                            fn = jit_program(
                                pair_programs[pi], split_complex, precision
                            )
                            held[x] = fn([held.pop(x), moved])
                        except Exception as exc:  # noqa: BLE001
                            raise PartitionExecutionError(
                                x, dev_slot[x], exc,
                                process=me, phase="fanin",
                            ) from exc
                        level_flops += pair_flops[pi]
                    pi += 1
                if obs.enabled():
                    level_sp.add(bytes=level_bytes, flops=level_flops)
                total_bytes += level_bytes
                total_flops += level_flops
        if obs.enabled():
            fanin_sp.add(
                bytes=total_bytes, flops=total_flops, cross_pairs=cross
            )

    root_part = _fanin_survivor(k, flat) if flat else 0
    if not flat:
        final_meta = metas[root_part]
    data = None
    if owner[root_part] == me:
        final = held[root_part]
        if split_complex:
            from tnc_tpu.ops.split_complex import combine_array

            data = combine_array(*final)
        else:
            data = np.asarray(final)
        data = data.reshape(tuple(final_meta.bond_dims))
    # every process returns the same tensor (byte-exact KV transport)
    data = broadcast_object(data, root=owner[root_part])
    return LeafTensor(
        list(final_meta.legs), list(final_meta.bond_dims),
        TensorData.matrix(data),
    )


def distributed_partitioned_contraction(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list | None = None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    hbm_bytes: int | None = None,
    local_sliced_strategy: str = "chunked",
    slice_batch: int = 8,
    chunk_steps: int = 64,
    hoist: bool = False,
    communication_scheme=None,
    cost_model=None,
    process_sharded: bool | None = None,
) -> LeafTensor:
    """Contract a partitioned network with one partition per device.

    ``tn`` must be the output of ``partition_tensor_network`` (top-level
    children = partitions) and ``contract_path`` must carry a nested path
    per partition plus the toplevel communication schedule — the same
    contract as the reference's distributed pipeline (§3.2 of SURVEY.md).
    ``hbm_bytes`` sets a per-device budget; partitions that exceed it are
    locally sliced (partitioning × slicing composition).
    ``local_sliced_strategy``/``slice_batch``/``chunk_steps`` select the
    executor for those locally sliced partitions ('chunked' — the fast
    path on real TPUs — or 'loop', one dispatch per partition, fine on
    virtual CPU meshes); ``hoist=True`` additionally runs each sliced
    partition's slice-invariant stem once (:mod:`tnc_tpu.ops.hoist`).

    ``communication_scheme`` (a :class:`~tnc_tpu.contractionpath.
    communication_schemes.CommunicationScheme`): re-derive the fan-in
    schedule here via :func:`replan_fanin` — with per-partition
    latencies always populated (calibrated seconds under ``cost_model``)
    — instead of trusting ``contract_path.toplevel``.

    ``process_sharded``: shard partitions across host processes
    (:func:`_process_sharded_contraction` — local contraction per host,
    cross-host fan-in over the coordination-KV transport, bit-identical
    to the single-host result). Default (``None``): automatic whenever
    the run is multi-process (``jax.distributed.initialize`` with
    ``jax.process_count() > 1``) *unless* an explicit ``devices`` /
    ``n_devices`` placement was given (the sharded executor places on
    each host's local devices itself, so it would silently ignore
    them — an explicit placement keeps the single-controller path, and
    combining one with ``process_sharded=True`` raises); pass ``False``
    to force the single-controller path (requires all devices
    addressable).
    """
    import jax

    if communication_scheme is not None:
        contract_path = replan_fanin(
            tn, contract_path, communication_scheme, cost_model
        )
    explicit_placement = devices is not None or n_devices is not None
    if process_sharded is None:
        process_sharded = jax.process_count() > 1 and not explicit_placement
    if process_sharded:
        if explicit_placement:
            raise ValueError(
                "process_sharded=True places partitions on each host's "
                "local devices itself; devices/n_devices cannot be "
                "combined with it"
            )
        return _process_sharded_contraction(
            tn, contract_path, dtype, split_complex, precision, hbm_bytes,
            local_sliced_strategy, slice_batch, chunk_steps, hoist,
        )
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}"
                )
            devices = devices[:n_devices]
    if split_complex is None:
        split_complex = devices[0].platform != "cpu"

    comm, buffers = scatter_partitions(
        tn, contract_path, devices, dtype, split_complex, hbm_bytes=hbm_bytes
    )
    results = local_contract_partitions(
        comm,
        buffers,
        split_complex,
        precision,
        sliced_strategy=local_sliced_strategy,
        dtype=dtype,
        slice_batch=slice_batch,
        chunk_steps=chunk_steps,
        hoist=hoist,
    )
    final, meta = intermediate_reduce(
        comm, contract_path.toplevel, results, split_complex, precision
    )

    if split_complex:
        from tnc_tpu.ops.split_complex import combine_array

        data = combine_array(*final)
    else:
        data = np.asarray(final)
    # device buffers live in stored (merged) shape; restore leg granularity
    data = data.reshape(tuple(meta.bond_dims))
    return LeafTensor(list(meta.legs), list(meta.bond_dims), TensorData.matrix(data))


def flatten_partitioned_path(
    tn: CompositeTensor, contract_path: ContractionPath
) -> tuple[list[LeafTensor], list[tuple[int, int]]]:
    """Inline a partitioned path into one flat replace-left path over the
    global leaf list (children in index order, as `flat_leaf_tensors`
    orders them) — the form the slicing planner consumes.

    >>> import random
    >>> from tnc_tpu.contractionpath.repartitioning import compute_solution
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [2, 2]),
    ...     LeafTensor([1, 2], [2, 2]), LeafTensor([2, 3], [2, 2]),
    ...     LeafTensor([3, 0], [2, 2])])
    >>> ptn, ppath, _, _ = compute_solution(tn, [0, 0, 1, 1],
    ...     rng=random.Random(0))
    >>> leaves, pairs = flatten_partitioned_path(ptn, ppath)
    >>> len(leaves), len(pairs)   # 4 leaves, fully contracted
    (4, 3)
    """
    flat_leaves: list[LeafTensor] = []
    start: dict[int, int] = {}
    children = list(tn.tensors)
    for ci, child in enumerate(children):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {ci} is not a partition composite")
        start[ci] = len(flat_leaves)
        flat_leaves.extend(child.tensors)  # type: ignore[arg-type]

    pairs: list[tuple[int, int]] = []
    rep: dict[int, int] = {}
    for ci, child in enumerate(children):
        local = contract_path.nested[ci].toplevel
        base = start[ci]
        for i, j in local:
            pairs.append((base + i, base + j))
        rep[ci] = base + _fanin_survivor(len(child.tensors), local)
    for x, y in contract_path.toplevel:
        pairs.append((rep[x], rep[y]))
    return flat_leaves, pairs


def distributed_partitioned_sliced_contraction(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list | None = None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    hbm_bytes: int | None = None,
    target_size: float | None = None,
    max_slices: int | None = None,
) -> tuple[LeafTensor, "Slicing"]:
    """Partitioning × **global** slicing (BASELINE config #5; the
    composition the reference lists as future work,
    ``book/src/future_work.md`` item 2).

    Legs are sliced across the *whole* network — including partition cut
    edges, which shrinks the externals that dominate partition memory —
    and for every slice index each device contracts its partition
    concurrently, the fan-in schedule reduces the per-slice result over
    the devices, and results accumulate on the root device.

    ``target_size`` (elements) fixes the slicing directly; otherwise it
    is derived from ``hbm_bytes`` (default: the device's budget).
    ``max_slices`` caps the loop (benchmark subset mode — the sum is then
    partial). Returns (result leaf, slicing).
    """
    run, slicing, final_meta = partitioned_sliced_executor(
        tn,
        contract_path,
        devices=devices,
        n_devices=n_devices,
        dtype=dtype,
        split_complex=split_complex,
        precision=precision,
        hbm_bytes=hbm_bytes,
        target_size=target_size,
    )
    data = run(max_slices)
    return (
        LeafTensor(
            list(final_meta.legs),
            list(final_meta.bond_dims),
            TensorData.matrix(data),
        ),
        slicing,
    )


def global_slicing_target(hbm_bytes: float) -> float:
    """Per-slice element target for the composed pipeline: padded
    split-complex working set ~8 bytes/elem x ~8 live copies."""
    return max(float(hbm_bytes) / 64.0, 4.0)


def plan_global_slicing(
    flat_leaves, flat_pairs, target_size: float, max_slices: int = 1 << 24
):
    """Find the global slicing for a flattened partitioned path at
    ``target_size`` elements, relaxing the target 4x at a time when it
    needs more slices than ``max_slices`` (the per-slice footprint
    then overshoots the budget — best effort; the caller sees the
    slicing and can re-plan). Host-only: benchmark plan ranking calls
    this without touching devices.

    ``max_slices`` defaults to the executable regime (2^24 sequential
    rounds is already far beyond any practical run); PLAN RANKING may
    pass a deep cap (2^40) so budget-infeasible candidates are
    recognized rather than silently relaxed — an executor must never
    inherit that cap, or a degenerate tiny-peak network turns into a
    billion-iteration slice loop (measured round 5: the multichip
    dryrun's 36-element network)."""
    from tnc_tpu.contractionpath.slicing import find_slicing

    while True:
        try:
            return find_slicing(
                flat_leaves, flat_pairs, target_size, max_slices=max_slices
            )
        except ValueError:
            if target_size > 2.0**62:
                raise
            target_size *= 4.0
            logger.warning(
                "global slicing target relaxed to %g elements", target_size
            )


def partitioned_sliced_executor(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list | None = None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    hbm_bytes: int | None = None,
    target_size: float | None = None,
    plan_max_slices: int = 1 << 24,
):
    """Compile the partitioned × globally-sliced pipeline once and return
    ``(run, slicing, final_meta)`` where ``run(max_slices=None)`` executes
    the slice loop (partial sum when capped) and returns the accumulated
    host array — compiled executables are reused across calls (the
    benchmark warms up with one slice, then times a subset).

    ``plan_max_slices``: forwarded to :func:`plan_global_slicing` — the
    benchmark passes its deep ranking cap (2^40) so the slicing the
    executor compiles is the SAME one the strategy rank scored (probe
    subsets keep deep slice sets affordable); interactive callers keep
    the executable default."""
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import _run_steps
    from tnc_tpu.ops.budget import device_hbm_bytes
    from tnc_tpu.ops.sliced import (
        _slice_indices,
        build_sliced_program,
        index_buffer,
    )
    from tnc_tpu.ops.split_complex import plan_kernels, run_steps_split

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(f"need {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    if split_complex is None:
        split_complex = devices[0].platform != "cpu"

    flat_leaves, flat_pairs = flatten_partitioned_path(tn, contract_path)
    if target_size is None:
        if hbm_bytes is None:
            hbm_bytes = device_hbm_bytes(devices[0])
        target_size = global_slicing_target(hbm_bytes)
    slicing = plan_global_slicing(
        flat_leaves, flat_pairs, target_size, max_slices=plan_max_slices
    )
    logger.debug(
        "global slicing: %d legs, %d slices (target %g elems)",
        len(slicing.legs),
        slicing.num_slices,
        target_size,
    )

    children = list(tn.tensors)
    k = len(children)
    mapping = DeviceTensorMapping.for_path(k, contract_path.toplevel)
    sps = [
        build_sliced_program(child, contract_path.nested[i], slicing)
        for i, child in enumerate(children)
    ]
    metas = [
        LeafTensor(list(sp.program.result_legs), list(sp.program.result_shape))
        for sp in sps
    ]
    buffers = [
        place_buffers(
            _leaf_arrays(child), dtype, split_complex, devices[mapping.device(i)]
        )
        for i, child in enumerate(children)
    ]

    def make_local_fn(sp):
        def fn(bufs, indices):
            if split_complex:
                sliced = [
                    (
                        index_buffer(jnp, re, info, indices),
                        index_buffer(jnp, im, info, indices),
                    )
                    for (re, im), info in zip(bufs, sp.slot_slices)
                ]
                return run_steps_split(
                    jnp, sp.program, sliced, precision,
                    policy=plan_kernels(sp.program),
                )
            sliced = [
                index_buffer(jnp, arr, info, indices)
                for arr, info in zip(bufs, sp.slot_slices)
            ]
            return _run_steps(jnp, sp.program, list(sliced))

        return jax.jit(fn)

    local_fns = [make_local_fn(sp) for sp in sps]

    # fan-in pair programs are slice-independent (legs already reduced);
    # the level schedule groups independent pairs so each slice's reduce
    # dispatches a level back-to-back (async) with no host sync between
    # same-level pairs
    from tnc_tpu.contractionpath.communication_schemes import fanin_levels

    levels = fanin_levels(contract_path.toplevel)
    # programs indexed in FLATTENED level order (level grouping may
    # reorder independent pairs relative to the path; the tree — which
    # tensors meet — is unchanged, so the programs and survivor are too)
    flat_pairs = [pair for level in levels for pair in level]
    pair_programs, _moved_metas, pair_flops, final_meta = plan_fanin_pairs(
        metas, flat_pairs
    )
    root = _fanin_survivor(k, flat_pairs) if flat_pairs else 0
    if not flat_pairs:
        final_meta = metas[root]

    def run(max_slices: int | None = None):
        num = slicing.num_slices if max_slices is None else min(
            slicing.num_slices, max_slices
        )
        with obs.maybe_jax_profiler_trace(), obs.span(
            "partitioned.sliced_run", slices=num, partitions=k
        ):
            acc = _run_slices(num)

        if split_complex:
            from tnc_tpu.ops.split_complex import combine_array

            data = combine_array(*acc)
        else:
            data = np.asarray(acc)
        return data.reshape(tuple(final_meta.bond_dims))

    def _fanin_one_slice(held: list, record_spans: bool):
        """One slice's tree reduce: level-grouped async dispatch, the
        survivor stays resident on the root device. Spans (recorded for
        the first slice of a run only — one schedule, many identical
        slices) carry per-level pairs/bytes/flops for the roofline and
        the bench ``distributed`` block."""
        pi = 0
        for li, level in enumerate(levels):
            with (
                obs.span(
                    "partitioned.fanin_level", level=li, pairs=len(level)
                )
                if record_spans
                else contextlib.nullcontext()
            ) as level_sp:
                level_bytes = 0.0
                level_flops = 0.0
                for x, y in level:
                    target = devices[mapping.device(x)]
                    moved = jax.device_put(held[y], target)
                    pair_fn = jit_program(
                        pair_programs[pi], split_complex, precision,
                        donate=False,
                    )
                    level_bytes += _buffer_nbytes(held[y])
                    level_flops += pair_flops[pi]
                    held[x] = pair_fn([held[x], moved])
                    held[y] = None
                    pi += 1
                if record_spans and obs.enabled():
                    level_sp.add(bytes=level_bytes, flops=level_flops)
        return held

    def _run_slices(num: int):
        acc = None
        for s in range(num):
            # host (uncommitted) indices: each jit transfers them to its
            # own partition's device
            indices = np.asarray(_slice_indices(slicing, s), dtype=np.int32)
            held = [
                fn(bufs, indices) for fn, bufs in zip(local_fns, buffers)
            ]  # async: all devices work concurrently
            held = _fanin_one_slice(held, record_spans=(s == 0))
            if acc is None:
                acc = held[root]
            elif split_complex:
                acc = (acc[0] + held[root][0], acc[1] + held[root][1])
            else:
                acc = acc + held[root]
        return acc

    return run, slicing, final_meta


# process-local counter giving every broadcast_object call a unique,
# deterministic KV key. broadcast_object is a collective: all processes
# call it the same number of times in the same order, so their counters
# agree by construction.
_KV_BCAST_SEQ = 0
_KV_BCAST_TIMEOUT_MS = 120_000


def _coordination_client():
    """The jax distributed coordination-service client (the same TCP
    channel ``jax.distributed.initialize`` already established), or
    ``None`` when unavailable (old jaxlib, or no distributed runtime).
    Private-API access is isolated here on purpose."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — any API drift → collective fallback
        return None


def broadcast_object(
    obj,
    root: int = 0,
    wait_forever: bool = False,
    timeout_s: float | None = None,
):
    """Broadcast any picklable object from host process ``root`` to all
    processes — the generic transport under :func:`broadcast_path` and
    the cross-process fan-in (the reference's serialized MPI broadcast,
    ``mpi/communication.rs:14-28``).

    Identity when running single-process; non-root processes pass any
    value (it is ignored) and receive root's object.

    ``wait_forever``: keep re-arming the KV wait past the transport
    timeout instead of raising — the serving fleet's command channel
    (:mod:`tnc_tpu.serve.multihost`), where a worker legitimately
    blocks on the *next* command through arbitrarily long idle periods.
    The per-call sequence key is armed exactly once, so retried waits
    stay in lockstep with the sender.

    ``timeout_s``: bound EVERY wait in this call (the payload get and
    the cleanup barrier) instead of the 120 s transport default. An
    expired wait raises :class:`TimeoutError` — which
    :func:`~tnc_tpu.resilience.retry.classify_exception` maps to
    TRANSIENT — so an elastic fleet's command round degrades to a
    retry/reassign decision instead of hanging on a dead peer. Ignored
    by ``wait_forever`` (which re-arms by design).

    Transport: the distributed **coordination-service KV store** (root
    ``key_value_set``s the pickled payload under a per-call sequence
    key; everyone else blocks on it) — control-plane metadata rides the
    same reliable TCP channel ``jax.distributed.initialize`` set up,
    not the accelerator data plane. The previous transport
    (``multihost_utils.broadcast_one_to_all``, a device psum) was
    observed to silently return ZEROS for the payload phase on
    oversubscribed CPU/gloo test clusters — a corrupted path, not an
    error — which is exactly the failure mode a control channel must
    not have. The collective path is kept as a verified fallback for
    environments without a coordination client.
    """
    import jax

    if jax.process_count() == 1:
        return obj

    import pickle

    global _KV_BCAST_SEQ
    is_root = jax.process_index() == root

    client = _coordination_client()
    if client is not None:
        import base64

        timeout_ms = (
            max(int(float(timeout_s) * 1000.0), 1)
            if timeout_s is not None else _KV_BCAST_TIMEOUT_MS
        )
        seq = _KV_BCAST_SEQ
        _KV_BCAST_SEQ += 1
        key = f"tnc_tpu/bcast/{root}/{seq}"
        if is_root:
            client.key_value_set(
                key, base64.b64encode(pickle.dumps(obj)).decode("ascii")
            )
        while True:
            try:
                blob = client.blocking_key_value_get(key, timeout_ms)
                break
            except Exception as exc:  # noqa: BLE001 — deadline probe
                if "deadline" in str(exc).lower():
                    if wait_forever:
                        continue  # same key: the sender hasn't spoken yet
                    raise TimeoutError(
                        f"broadcast wait for {key} expired after "
                        f"{timeout_ms} ms (sender dead or stalled)"
                    ) from exc
                raise
        out = pickle.loads(base64.b64decode(blob))
        # reclaim the key: a barrier proves every process has read it,
        # then the root deletes — without this, a long-running job's
        # pickled payloads accumulate in the coordination service
        # forever. Best-effort: on any barrier/delete hiccup the key
        # simply stays resident (leak-not-break) — and a dead peer
        # stalls the live fleet here only for timeout_ms, never forever.
        try:
            client.wait_at_barrier(
                f"tnc_tpu/bcast_done/{root}/{seq}", timeout_ms
            )
            if is_root:
                client.key_value_delete(key)
        except Exception:  # noqa: BLE001 — cleanup must never fail a bcast
            logger.debug("bcast key cleanup skipped for %s", key)
        return out

    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj) if is_root else b""
    # length-prefix phase (the reference broadcasts the length first)
    length = int(
        multihost_utils.broadcast_one_to_all(
            np.int64(len(payload)), is_source=is_root
        )
    )
    buf = np.frombuffer(payload.ljust(length, b"\0"), dtype=np.uint8)
    data = multihost_utils.broadcast_one_to_all(buf, is_source=is_root)
    raw = np.asarray(data).tobytes()
    try:
        return pickle.loads(raw)
    except Exception as exc:
        # turn the silent-zeros corruption mode into a diagnosable error
        raise RuntimeError(
            "collective object broadcast returned a corrupt payload "
            f"({len(raw)} bytes, {sum(b != 0 for b in raw[:64])} non-zero "
            "of the first 64) — the CPU/gloo collective backend on this "
            "host is unreliable; jax's coordination-service client was "
            "unavailable for the KV fallback"
        ) from exc


class GatherLost:
    """Root-side placeholder for a gather slot whose sender never
    delivered within the timeout (dead or stalled process). Carries the
    source process index; only ever appears in :func:`gather_objects`
    output when ``missing_ok=True``."""

    def __init__(self, process: int):
        self.process = int(process)

    def __repr__(self) -> str:
        return f"GatherLost(process={self.process})"


def gather_objects(
    obj,
    root: int = 0,
    timeout_s: float | None = None,
    missing_ok: bool = False,
) -> list | None:
    """Gather one picklable object per process at ``root``: returns the
    per-process list (index = process) on the root, ``None`` elsewhere.
    The collective inverse of :func:`broadcast_object` — and unlike a
    gather built from n-1 broadcasts, only the root reads the payloads
    (each sender ``key_value_set``s under its own slot of one shared
    sequence key; total transfer is O(n · payload), one cleanup barrier
    per call). Every process must call this in the same collective
    order; the serving fleet's batch gather rides it
    (:mod:`tnc_tpu.serve.multihost`).

    ``timeout_s`` bounds the root's whole collection (a shared deadline
    across slots, floor 1 s per remaining slot) and the cleanup barrier
    on every process. An expired slot raises :class:`TimeoutError`
    (TRANSIENT under :func:`~tnc_tpu.resilience.retry.
    classify_exception`) — or, with ``missing_ok=True``, lands a
    :class:`GatherLost` marker in that slot so the caller can reassign
    the lost work instead of failing the round (the elastic fleet's
    worker-loss path, :mod:`tnc_tpu.serve.elastic`).

    Identity when running single-process (returns ``[obj]``). Falls
    back to n-1 :func:`broadcast_object` rounds when the coordination
    client is unavailable.
    """
    import jax

    n = jax.process_count()
    if n == 1:
        return [obj]

    import pickle

    global _KV_BCAST_SEQ
    me = jax.process_index()
    client = _coordination_client()
    if client is None:
        # collective fallback: everyone hears everything (n-1 bcasts)
        parts = []
        for src in range(n):
            got = broadcast_object(obj if me == src else None, root=src)
            parts.append(got)
        return parts if me == root else None

    import base64

    timeout_ms = (
        max(int(float(timeout_s) * 1000.0), 1)
        if timeout_s is not None else _KV_BCAST_TIMEOUT_MS
    )
    seq = _KV_BCAST_SEQ
    _KV_BCAST_SEQ += 1
    prefix = f"tnc_tpu/gather/{root}/{seq}"
    if me != root:
        client.key_value_set(
            f"{prefix}/{me}",
            base64.b64encode(pickle.dumps(obj)).decode("ascii"),
        )
    parts = None
    if me == root:
        parts = [None] * n
        parts[root] = obj
        deadline = time.monotonic() + timeout_ms / 1000.0
        for src in range(n):
            if src == root:
                continue
            remaining_ms = max(
                int((deadline - time.monotonic()) * 1000.0), 1000
            )
            try:
                blob = client.blocking_key_value_get(
                    f"{prefix}/{src}", remaining_ms
                )
            except Exception as exc:  # noqa: BLE001 — deadline probe
                if "deadline" not in str(exc).lower():
                    raise
                if not missing_ok:
                    raise TimeoutError(
                        f"gather wait for process {src} expired after "
                        f"{remaining_ms} ms (process dead or stalled)"
                    ) from exc
                parts[src] = GatherLost(src)
                continue
            parts[src] = pickle.loads(base64.b64decode(blob))
    # reclaim: the barrier proves the root has read every slot, then
    # each sender deletes its own key (best-effort, leak-not-break;
    # a dead peer stalls everyone here only for timeout_ms)
    try:
        client.wait_at_barrier(
            f"tnc_tpu/gather_done/{root}/{seq}", timeout_ms
        )
        if me != root:
            client.key_value_delete(f"{prefix}/{me}")
    except Exception:  # noqa: BLE001 — cleanup must never fail a gather
        logger.debug("gather key cleanup skipped for %s", prefix)
    return parts


def p2p_sequence() -> int | None:
    """Reserve one point-to-point key namespace for the calling
    collective. EVERY process must call this at the same point of the
    same collective (it advances the shared sequence counter, keeping
    all later :func:`broadcast_object` keys aligned) even though only
    a sender/receiver pair touches each :func:`send_object` /
    :func:`recv_object` slot under it. Returns ``None`` when no
    coordination client is available — callers fall back to the
    all-process :func:`broadcast_object` transport."""
    global _KV_BCAST_SEQ
    seq = _KV_BCAST_SEQ
    _KV_BCAST_SEQ += 1
    return seq if _coordination_client() is not None else None


def send_object(obj, seq: int, slot: int) -> None:
    """Point-to-point send: publish ``obj`` under slot ``slot`` of the
    :func:`p2p_sequence` namespace ``seq``. Non-blocking; only the one
    consumer (:func:`recv_object`) reads it — O(payload) total traffic
    where a :func:`broadcast_object` costs O(n_processes · payload) and
    a blocking read on every host."""
    import base64
    import pickle

    _coordination_client().key_value_set(
        f"tnc_tpu/p2p/{seq}/{slot}",
        base64.b64encode(pickle.dumps(obj)).decode("ascii"),
    )


def recv_object(seq: int, slot: int):
    """Point-to-point receive half of :func:`send_object`. The receiver
    is the slot's only consumer, so it reclaims the key itself after
    reading — no fleet barrier (best-effort: a delete hiccup leaks the
    key, never breaks the transfer)."""
    import base64
    import pickle

    client = _coordination_client()
    key = f"tnc_tpu/p2p/{seq}/{slot}"
    blob = client.blocking_key_value_get(key, _KV_BCAST_TIMEOUT_MS)
    out = pickle.loads(base64.b64decode(blob))
    try:
        client.key_value_delete(key)
    except Exception:  # noqa: BLE001 — cleanup must never fail a recv
        logger.debug("p2p key cleanup skipped for %s", key)
    return out


def broadcast_path(path_: ContractionPath, root: int = 0) -> ContractionPath:
    """Share the planner's path with every host process
    (``broadcast_path``, ``communication.rs:32-49``).

    Under JAX's single-controller model a single process plans and
    executes, so this is the identity; in a multi-process run
    (``jax.distributed.initialize``) the path found by the ``root``
    process is broadcast to all others as serialized bytes over the
    global mesh, the analogue of the reference's two-phase MPI vec
    broadcast (``communication.rs:14-28``).
    """
    return broadcast_object(path_, root=root)


# Reference-named aliases (``mpi/communication.rs:125,199``): the TPU
# executor's scatter/reduce are the same pipeline stages under the
# device-mesh model.
scatter_tensor_network = scatter_partitions
intermediate_reduce_tensor_network = intermediate_reduce
# the reference's generic serialized broadcast (``broadcast_serializing``,
# ``mpi/communication.rs:14-28``) — any picklable object from root to all
broadcast_serializing = broadcast_object
