"""Partition-parallel distributed contraction over JAX devices.

TPU-native equivalent of the reference's MPI runtime
(``tnc/src/mpi/communication.rs``). The reference's pipeline is

    rank 0: partition → per-partition paths → toplevel fan-in path
    broadcast_path / scatter_tensor_network    (bcast + p2p sends)
    every rank: contract its partition locally (zero communication)
    intermediate_reduce_tensor_network         (pairwise p2p fan-in)

Here the same schedule runs under JAX's single-controller model:

- *Scatter* = ``jax.device_put`` of each partition's leaf tensors onto its
  device. No serialization layer is needed (the reference needs postcard +
  192-byte MPI blobs, ``mpi/serialization.rs``, ``mpi_types.rs:73-83``);
  arrays move host→HBM directly.
- *Local phase* = each partition's whole nested path compiled to one XLA
  program and dispatched to its device. JAX dispatch is asynchronous, so
  all devices compute their partitions **concurrently** — the analogue of
  the independent per-rank contraction phase.
- *Fan-in reduce* = the ``toplevel`` path interpreted as a communication
  schedule, exactly like ``intermediate_reduce_tensor_network``
  (``communication.rs:199-249``): for each pair ``(x, y)`` the tensor held
  by ``y``'s device is ``device_put`` onto ``x``'s device (a direct
  device-to-device copy — ICI on a TPU slice) and contracted there.
- *Final tensor on device 0*: ``DeviceTensorMapping`` assigns the
  partition that survives the fan-in to device 0, mirroring
  ``get_tensor_mapping`` reserving rank 0 (``communication.rs:89-115``).

Multi-host scaling: under ``jax.distributed.initialize`` the same code
addresses every device in the pod; ``device_put`` between hosts rides
DCN. There is no rank-local control flow to port.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

logger = logging.getLogger(__name__)

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.ops.backends import jit_program, place_buffers
from tnc_tpu.ops.program import (
    ContractionProgram,
    _pair_step,
    build_program,
)
from tnc_tpu.resilience import faultinject as _faults
from tnc_tpu.resilience import retry as _retry
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


class PartitionExecutionError(RuntimeError):
    """A partition's local contraction failed; names the partition index
    and device slot so a pool-surfaced XLA error is attributable
    (``pool.map`` otherwise raises a bare runtime error with no hint of
    which partition died). Chains the original (``__cause__``)."""

    def __init__(self, partition: int, device: int, original: BaseException):
        super().__init__(
            f"partition {partition} on device {device} failed: "
            f"{type(original).__name__}: {original}"
        )
        self.partition = partition
        self.device = device
        self.original = original

def partition_latency_map(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    cost_model=None,
) -> dict[int, float]:
    """Per-partition local completion latencies for fan-in scheduling —
    never ``None``-filled: predicted seconds under ``cost_model`` (a
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`, dispatch
    overhead charged per local step), raw local op counts otherwise.

    This is what the latency-aware communication schemes
    (``WEIGHTED_BRANCH_BOUND``, ``BIPARTITION_SWEEP``) must receive on
    the partitioned path: with an empty latency map every partition
    looks instantly available and the "latency-aware" schedule
    degenerates to a plain flops fan-in.
    """
    from tnc_tpu.contractionpath.contraction_cost import contract_path_cost

    latency: dict[int, float] = {}
    steps: dict[int, float] = {}
    for i, child in enumerate(tn.tensors):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {i} is not a partition composite")
        if i not in contract_path.nested:
            raise ValueError(f"partition {i} has no nested contraction path")
        local = contract_path.nested[i]
        flops, _ = contract_path_cost(child.tensors, local, True)
        latency[i] = flops
        steps[i] = float(len(local.toplevel))
    if cost_model is not None:
        from tnc_tpu.contractionpath.communication_schemes import (
            calibrated_latency_map,
        )

        latency = calibrated_latency_map(latency, cost_model, steps)
    return latency


def replan_fanin(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    communication_scheme,
    cost_model=None,
    rng=None,
) -> ContractionPath:
    """Re-derive the toplevel fan-in schedule of a partitioned path with
    a latency-aware communication scheme, keeping the nested local
    paths. The latency map comes from :func:`partition_latency_map` —
    calibrated seconds when a ``cost_model`` is given — so deferring a
    slow partition's tensor is priced against real completion times.
    """
    import random as _random

    latency = partition_latency_map(tn, contract_path, cost_model)
    children = [
        child.external_tensor() for child in tn.tensors
    ]  # type: ignore[union-attr]
    toplevel = communication_scheme.communication_path(
        children,
        latency,
        rng if rng is not None else _random.Random(42),
        cost_model=cost_model,
    )
    return ContractionPath(dict(contract_path.nested), list(toplevel))


def _fanin_survivor(k: int, toplevel: Sequence[tuple[int, int]]) -> int:
    """Index that holds the final tensor after a replace-left fan-in."""
    alive = [True] * k
    for x, y in toplevel:
        if not (alive[x] and alive[y]):
            raise ValueError(f"communication path reuses a consumed index: {(x, y)}")
        alive[y] = False
    survivors = [i for i, a in enumerate(alive) if a]
    if len(survivors) != 1:
        raise ValueError(
            f"communication path leaves {len(survivors)} tensors, expected 1"
        )
    return survivors[0]


@dataclass(frozen=True)
class DeviceTensorMapping:
    """Partition index ↔ device, final-result partition pinned to device 0.

    Equivalent of ``RankTensorMapping`` (``mpi/mpi_types.rs:11-62``) +
    ``get_tensor_mapping`` (``communication.rs:89-115``).
    """

    device_of_partition: tuple[int, ...]  # partition i → device slot

    @classmethod
    def for_path(
        cls, k: int, toplevel: Sequence[tuple[int, int]]
    ) -> "DeviceTensorMapping":
        root = _fanin_survivor(k, toplevel)
        order = [root] + [i for i in range(k) if i != root]
        device_of = [0] * k
        for slot, part in enumerate(order):
            device_of[part] = slot
        return cls(tuple(device_of))

    def device(self, partition: int) -> int:
        return self.device_of_partition[partition]


@dataclass
class Communication:
    """Executor state for one distributed contraction (cf. ``Communication``
    in ``communication.rs:118-122``).

    ``programs[i]`` is either a :class:`ContractionProgram` (partition
    fits HBM) or a :class:`~tnc_tpu.ops.sliced.SlicedProgram` (partition
    sliced to fit — the slicing × partitioning composition the reference
    lists as future work, ``book/src/future_work.md`` item 2)."""

    mapping: DeviceTensorMapping
    devices: list
    programs: list[Any]
    results_meta: list[LeafTensor]


def _pair_program(ta: LeafTensor, tb: LeafTensor) -> tuple[ContractionProgram, LeafTensor]:
    step, result = _pair_step(0, 1, ta, tb)
    program = ContractionProgram(
        num_inputs=2,
        steps=(step,),
        result_slot=0,
        result_legs=tuple(result.legs),
        result_shape=tuple(result.bond_dims),
    )
    return program, result


def _leaf_arrays(child: CompositeTensor) -> list[np.ndarray]:
    from tnc_tpu.ops.program import flat_leaf_tensors

    return [np.asarray(leaf.data.into_data()) for leaf in flat_leaf_tensors(child)]


def _slice_partition(child: CompositeTensor, nested: ContractionPath, hbm_bytes: int):
    """Slice one partition's local path until its program fits the HBM
    budget. Returns a SlicedProgram (or None if the unsliced program
    already fits, or nothing local slicing can do).

    Uses slice-and-reconfigure (slicing interleaved with subtree
    re-planning in the sliced size model) rather than plain greedy leg
    picking: a fixed path's peak is often pinned by a single badly-
    ordered step that reconfiguration dissolves once the sliced legs
    have dim 1. The returned ``SlicedProgram``'s program may therefore
    follow a DIFFERENT (better) local path than ``nested`` — downstream
    fan-in metadata must come from ``sp.program.result_legs`` (it does:
    ``scatter_partitions`` builds metas from the program).
    """
    from tnc_tpu.contractionpath.contraction_path import replace_ssa_ordering
    from tnc_tpu.contractionpath.slicing import slice_and_reconfigure
    from tnc_tpu.ops.budget import fits_hbm, program_peak_bytes
    from tnc_tpu.ops.sliced import build_sliced_program

    program = build_program(child, nested)
    if fits_hbm(program, hbm_bytes=hbm_bytes):
        return None
    if nested.nested:
        raise ValueError(
            "HBM budget exceeded on a partition with a nested local path; "
            "slicing supports flat partition paths"
        )
    inputs = [t for t in child.tensors if isinstance(t, LeafTensor)]
    est = program_peak_bytes(program)
    ssa = replace_ssa_ordering(nested.toplevel, len(inputs))
    # element targets, descending from a quarter of the current peak
    # (~8 bytes per complex element; starting AT the peak would be a
    # no-op): first slicing that fits the budget wins; keep the deepest
    # achievable as best effort. A partition whose peak is its own
    # open-leg output cannot be sliced locally at all — only GLOBAL
    # slicing (cut legs sliceable) helps there.
    target = 2.0 ** np.floor(np.log2(max(est.peak_bytes / 8.0 / 4.0, 2.0)))
    best = None
    while target >= 4:
        try:
            pairs, slicing = slice_and_reconfigure(
                inputs, ssa, target,
                reconf_rounds=1, step_budget=None,
                final_rounds=2, final_budget=None,
            )
        except ValueError:
            break
        if not slicing.legs:  # target above the current peak: no-op
            target /= 4.0
            continue
        sp = build_sliced_program(child, ContractionPath.simple(pairs), slicing)
        best = sp
        if fits_hbm(sp.program, hbm_bytes=hbm_bytes):
            break
        target /= 4.0
    if best is None:
        # nothing sliceable (open-leg-bound peak): run unsliced rather
        # than wrap a fake 1-slice program as success
        logger.warning(
            "partition peak %.3g bytes exceeds the %d-byte budget but has "
            "no sliceable (closed) legs; running unsliced — use global "
            "slicing (partitioned_sliced_executor) to slice cut legs",
            est.peak_bytes,
            hbm_bytes,
        )
        return None
    if not fits_hbm(best.program, hbm_bytes=hbm_bytes):
        logger.warning(
            "partition sliced best-effort (%d legs, %d slices) but still "
            "exceeds the %d-byte budget",
            len(best.slicing.legs),
            best.slicing.num_slices,
            hbm_bytes,
        )
    logger.debug(
        "partition sliced: %d legs, %d slices",
        len(best.slicing.legs),
        best.slicing.num_slices,
    )
    return best


def scatter_partitions(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list,
    dtype: str,
    split_complex: bool,
    hbm_bytes: int | None = None,
) -> tuple[Communication, list[list[Any]]]:
    """Compile per-partition programs and place each partition's leaves on
    its device (``scatter_tensor_network``, ``communication.rs:125-195``).

    With ``hbm_bytes`` set, any partition whose program exceeds the
    per-device budget is sliced locally (sum over slice programs on its
    own device) before the fan-in — composing partition parallelism with
    slicing.
    """
    children = list(tn.tensors)
    k = len(children)
    for i, child in enumerate(children):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {i} is not a partition composite")
        if i not in contract_path.nested:
            raise ValueError(f"partition {i} has no nested contraction path")
    if k > len(devices):
        raise ValueError(f"{k} partitions but only {len(devices)} devices")

    mapping = DeviceTensorMapping.for_path(k, contract_path.toplevel)

    programs: list[Any] = []
    metas: list[LeafTensor] = []
    buffers: list[list[Any]] = []
    with obs.span("partitioned.scatter", partitions=k):
        for i, child in enumerate(children):
            sp = None
            if hbm_bytes is not None:
                sp = _slice_partition(
                    child, contract_path.nested[i], hbm_bytes
                )
            if sp is not None:
                programs.append(sp)
                program = sp.program
            else:
                program = build_program(child, contract_path.nested[i])
                programs.append(program)
            metas.append(
                LeafTensor(
                    list(program.result_legs), list(program.result_shape)
                )
            )
            buffers.append(
                place_buffers(
                    _leaf_arrays(child), dtype, split_complex,
                    devices[mapping.device(i)],
                )
            )
            # mirror of "Scattering tensor network" (communication.rs:132)
            logger.debug(
                "scatter: partition %d -> device %d (%d tensors, %d steps%s)",
                i,
                mapping.device(i),
                len(child),
                len(program.steps),
                ", sliced" if sp is not None else "",
            )

    comm = Communication(mapping, list(devices), programs, metas)
    return comm, buffers


def local_contract_partitions(
    comm: Communication,
    buffers: list[list[Any]],
    split_complex: bool,
    precision,
    max_slices: int | None = None,
    sliced_strategy: str = "chunked",
    dtype: str = "complex64",
    slice_batch: int = 8,
    chunk_steps: int = 64,
    hoist: bool = False,
) -> list[Any]:
    """Dispatch every partition's compiled program to its device. Async
    dispatch → all devices run concurrently (the per-rank local phase).
    ``max_slices`` caps sliced partitions' loops (benchmark subset mode —
    the partial sums are NOT the correct partition tensors).
    ``hoist=True`` runs each sliced partition's slice-invariant stem
    once before its slice loop (:mod:`tnc_tpu.ops.hoist`).

    Sliced partitions run through the chunked executor by default (the
    on-device ``fori_loop`` is ~150× slower on real TPUs,
    TPU_EVIDENCE_r03.md); each partition's buffers are committed to its
    device, so the per-partition chunk dispatches execute there and the
    k local phases still overlap. ``sliced_strategy="loop"`` keeps the
    single-dispatch loop program (fewer host round-trips — the virtual
    CPU mesh doesn't pessimize loop bodies).

    First-run XLA compiles are driven from a thread pool: k distinct
    partition programs would otherwise compile back-to-back on the main
    thread (XLA compilation releases the GIL), serializing exactly the
    phase that should overlap. Warm runs take the sequential fast path.
    """
    if sliced_strategy not in ("chunked", "loop"):
        raise ValueError(
            f"unknown sliced_strategy {sliced_strategy!r}; "
            "expected 'chunked' or 'loop'"
        )
    logger.debug("local phase: %d partition programs", len(comm.programs))
    from tnc_tpu.ops.chunked import run_sliced_chunked_placed
    from tnc_tpu.ops.sliced import SlicedProgram, make_jax_sliced_fn

    def compile_one(i, program):
        if isinstance(program, SlicedProgram):
            if sliced_strategy == "chunked":
                dev = comm.devices[comm.mapping.device(i)]

                def run(bufs, _sp=program, _dev=dev):
                    return run_sliced_chunked_placed(
                        _sp,
                        bufs,
                        batch=slice_batch,
                        chunk_steps=chunk_steps,
                        split_complex=split_complex,
                        precision=precision,
                        dtype=dtype,
                        device=_dev,
                        max_slices=max_slices,
                        hoist=hoist,
                    )

                return run
            return make_jax_sliced_fn(
                program,
                split_complex=split_complex,
                precision=precision,
                num_slices=max_slices,
                hoist=hoist,
            )
        return jit_program(program, split_complex, precision)

    def run_job(i, fn, bufs):
        # runs on the pool worker thread, so each partition's span lands
        # on its own timeline lane (tid) in the exported trace
        dev = comm.mapping.device(i)
        with obs.span(
            "partitioned.local_partition",
            partition=i,
            device=dev,
        ):
            # transient failures retry THIS partition in place (bounded,
            # shared policy) instead of killing the pool with the other
            # partitions' finished work; anything that survives the
            # retries is re-raised naming the partition and device
            def _attempt():
                _faults.fault_point("partition.local", partition=i, device=dev)
                return fn(bufs)

            try:
                # unsliced partition programs dispatch with donated
                # inputs (jit_program default), so the donation guard
                # blocks retries once a failed dispatch consumed them
                return _retry.default_policy().run(
                    _attempt,
                    label="partition.local",
                    classify=_retry.donation_guarded_classify(bufs),
                )
            except Exception as exc:  # noqa: BLE001 — annotate and re-raise
                raise PartitionExecutionError(i, dev, exc) from exc

    jobs = [
        (i, compile_one(i, program), list(bufs))
        for i, (program, bufs) in enumerate(zip(comm.programs, buffers))
    ]
    with obs.span("partitioned.local", partitions=len(jobs)):
        if len(jobs) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
                return list(pool.map(lambda job: run_job(*job), jobs))
        return [run_job(i, fn, bufs) for i, fn, bufs in jobs]


def intermediate_reduce(
    comm: Communication,
    toplevel: Sequence[tuple[int, int]],
    results: list[Any],
    split_complex: bool,
    precision,
) -> tuple[Any, LeafTensor]:
    """Pairwise fan-in following the communication path
    (``intermediate_reduce_tensor_network``, ``communication.rs:199-249``):
    for ``(x, y)``, move y's tensor onto x's device and contract there.
    """
    import jax

    metas = list(comm.results_meta)
    held: list[Any] = list(results)
    with obs.span("partitioned.fanin", pairs=len(toplevel)):
        for x, y in toplevel:
            target = comm.devices[comm.mapping.device(x)]
            logger.debug(
                "fan-in: partition %d (device %d) <- partition %d (device %d)",
                x,
                comm.mapping.device(x),
                y,
                comm.mapping.device(y),
            )
            moved = jax.device_put(held[y], target)  # device-to-device (ICI)
            program, result_meta = _pair_program(metas[x], metas[y])
            fn = jit_program(program, split_complex, precision)
            held[x] = fn([held[x], moved])
            held[y] = None
            metas[x] = result_meta
    root = _fanin_survivor(len(held), toplevel) if toplevel else 0
    return held[root], metas[root]


def distributed_partitioned_contraction(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list | None = None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    hbm_bytes: int | None = None,
    local_sliced_strategy: str = "chunked",
    slice_batch: int = 8,
    chunk_steps: int = 64,
    hoist: bool = False,
    communication_scheme=None,
    cost_model=None,
) -> LeafTensor:
    """Contract a partitioned network with one partition per device.

    ``tn`` must be the output of ``partition_tensor_network`` (top-level
    children = partitions) and ``contract_path`` must carry a nested path
    per partition plus the toplevel communication schedule — the same
    contract as the reference's distributed pipeline (§3.2 of SURVEY.md).
    ``hbm_bytes`` sets a per-device budget; partitions that exceed it are
    locally sliced (partitioning × slicing composition).
    ``local_sliced_strategy``/``slice_batch``/``chunk_steps`` select the
    executor for those locally sliced partitions ('chunked' — the fast
    path on real TPUs — or 'loop', one dispatch per partition, fine on
    virtual CPU meshes); ``hoist=True`` additionally runs each sliced
    partition's slice-invariant stem once (:mod:`tnc_tpu.ops.hoist`).

    ``communication_scheme`` (a :class:`~tnc_tpu.contractionpath.
    communication_schemes.CommunicationScheme`): re-derive the fan-in
    schedule here via :func:`replan_fanin` — with per-partition
    latencies always populated (calibrated seconds under ``cost_model``)
    — instead of trusting ``contract_path.toplevel``.
    """
    import jax

    if communication_scheme is not None:
        contract_path = replan_fanin(
            tn, contract_path, communication_scheme, cost_model
        )
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}"
                )
            devices = devices[:n_devices]
    if split_complex is None:
        split_complex = devices[0].platform != "cpu"

    comm, buffers = scatter_partitions(
        tn, contract_path, devices, dtype, split_complex, hbm_bytes=hbm_bytes
    )
    results = local_contract_partitions(
        comm,
        buffers,
        split_complex,
        precision,
        sliced_strategy=local_sliced_strategy,
        dtype=dtype,
        slice_batch=slice_batch,
        chunk_steps=chunk_steps,
        hoist=hoist,
    )
    final, meta = intermediate_reduce(
        comm, contract_path.toplevel, results, split_complex, precision
    )

    if split_complex:
        from tnc_tpu.ops.split_complex import combine_array

        data = combine_array(*final)
    else:
        data = np.asarray(final)
    # device buffers live in stored (merged) shape; restore leg granularity
    data = data.reshape(tuple(meta.bond_dims))
    return LeafTensor(list(meta.legs), list(meta.bond_dims), TensorData.matrix(data))


def flatten_partitioned_path(
    tn: CompositeTensor, contract_path: ContractionPath
) -> tuple[list[LeafTensor], list[tuple[int, int]]]:
    """Inline a partitioned path into one flat replace-left path over the
    global leaf list (children in index order, as `flat_leaf_tensors`
    orders them) — the form the slicing planner consumes.

    >>> import random
    >>> from tnc_tpu.contractionpath.repartitioning import compute_solution
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [2, 2]),
    ...     LeafTensor([1, 2], [2, 2]), LeafTensor([2, 3], [2, 2]),
    ...     LeafTensor([3, 0], [2, 2])])
    >>> ptn, ppath, _, _ = compute_solution(tn, [0, 0, 1, 1],
    ...     rng=random.Random(0))
    >>> leaves, pairs = flatten_partitioned_path(ptn, ppath)
    >>> len(leaves), len(pairs)   # 4 leaves, fully contracted
    (4, 3)
    """
    flat_leaves: list[LeafTensor] = []
    start: dict[int, int] = {}
    children = list(tn.tensors)
    for ci, child in enumerate(children):
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"top-level child {ci} is not a partition composite")
        start[ci] = len(flat_leaves)
        flat_leaves.extend(child.tensors)  # type: ignore[arg-type]

    pairs: list[tuple[int, int]] = []
    rep: dict[int, int] = {}
    for ci, child in enumerate(children):
        local = contract_path.nested[ci].toplevel
        base = start[ci]
        for i, j in local:
            pairs.append((base + i, base + j))
        rep[ci] = base + _fanin_survivor(len(child.tensors), local)
    for x, y in contract_path.toplevel:
        pairs.append((rep[x], rep[y]))
    return flat_leaves, pairs


def distributed_partitioned_sliced_contraction(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list | None = None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    hbm_bytes: int | None = None,
    target_size: float | None = None,
    max_slices: int | None = None,
) -> tuple[LeafTensor, "Slicing"]:
    """Partitioning × **global** slicing (BASELINE config #5; the
    composition the reference lists as future work,
    ``book/src/future_work.md`` item 2).

    Legs are sliced across the *whole* network — including partition cut
    edges, which shrinks the externals that dominate partition memory —
    and for every slice index each device contracts its partition
    concurrently, the fan-in schedule reduces the per-slice result over
    the devices, and results accumulate on the root device.

    ``target_size`` (elements) fixes the slicing directly; otherwise it
    is derived from ``hbm_bytes`` (default: the device's budget).
    ``max_slices`` caps the loop (benchmark subset mode — the sum is then
    partial). Returns (result leaf, slicing).
    """
    run, slicing, final_meta = partitioned_sliced_executor(
        tn,
        contract_path,
        devices=devices,
        n_devices=n_devices,
        dtype=dtype,
        split_complex=split_complex,
        precision=precision,
        hbm_bytes=hbm_bytes,
        target_size=target_size,
    )
    data = run(max_slices)
    return (
        LeafTensor(
            list(final_meta.legs),
            list(final_meta.bond_dims),
            TensorData.matrix(data),
        ),
        slicing,
    )


def global_slicing_target(hbm_bytes: float) -> float:
    """Per-slice element target for the composed pipeline: padded
    split-complex working set ~8 bytes/elem x ~8 live copies."""
    return max(float(hbm_bytes) / 64.0, 4.0)


def plan_global_slicing(
    flat_leaves, flat_pairs, target_size: float, max_slices: int = 1 << 24
):
    """Find the global slicing for a flattened partitioned path at
    ``target_size`` elements, relaxing the target 4x at a time when it
    needs more slices than ``max_slices`` (the per-slice footprint
    then overshoots the budget — best effort; the caller sees the
    slicing and can re-plan). Host-only: benchmark plan ranking calls
    this without touching devices.

    ``max_slices`` defaults to the executable regime (2^24 sequential
    rounds is already far beyond any practical run); PLAN RANKING may
    pass a deep cap (2^40) so budget-infeasible candidates are
    recognized rather than silently relaxed — an executor must never
    inherit that cap, or a degenerate tiny-peak network turns into a
    billion-iteration slice loop (measured round 5: the multichip
    dryrun's 36-element network)."""
    from tnc_tpu.contractionpath.slicing import find_slicing

    while True:
        try:
            return find_slicing(
                flat_leaves, flat_pairs, target_size, max_slices=max_slices
            )
        except ValueError:
            if target_size > 2.0**62:
                raise
            target_size *= 4.0
            logger.warning(
                "global slicing target relaxed to %g elements", target_size
            )


def partitioned_sliced_executor(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    devices: list | None = None,
    n_devices: int | None = None,
    dtype: str = "complex64",
    split_complex: bool | None = None,
    precision: str | None = "float32",
    hbm_bytes: int | None = None,
    target_size: float | None = None,
    plan_max_slices: int = 1 << 24,
):
    """Compile the partitioned × globally-sliced pipeline once and return
    ``(run, slicing, final_meta)`` where ``run(max_slices=None)`` executes
    the slice loop (partial sum when capped) and returns the accumulated
    host array — compiled executables are reused across calls (the
    benchmark warms up with one slice, then times a subset).

    ``plan_max_slices``: forwarded to :func:`plan_global_slicing` — the
    benchmark passes its deep ranking cap (2^40) so the slicing the
    executor compiles is the SAME one the strategy rank scored (probe
    subsets keep deep slice sets affordable); interactive callers keep
    the executable default."""
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import _run_steps
    from tnc_tpu.ops.budget import device_hbm_bytes
    from tnc_tpu.ops.sliced import (
        _slice_indices,
        build_sliced_program,
        index_buffer,
    )
    from tnc_tpu.ops.split_complex import plan_kernels, run_steps_split

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(f"need {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    if split_complex is None:
        split_complex = devices[0].platform != "cpu"

    flat_leaves, flat_pairs = flatten_partitioned_path(tn, contract_path)
    if target_size is None:
        if hbm_bytes is None:
            hbm_bytes = device_hbm_bytes(devices[0])
        target_size = global_slicing_target(hbm_bytes)
    slicing = plan_global_slicing(
        flat_leaves, flat_pairs, target_size, max_slices=plan_max_slices
    )
    logger.debug(
        "global slicing: %d legs, %d slices (target %g elems)",
        len(slicing.legs),
        slicing.num_slices,
        target_size,
    )

    children = list(tn.tensors)
    k = len(children)
    mapping = DeviceTensorMapping.for_path(k, contract_path.toplevel)
    sps = [
        build_sliced_program(child, contract_path.nested[i], slicing)
        for i, child in enumerate(children)
    ]
    metas = [
        LeafTensor(list(sp.program.result_legs), list(sp.program.result_shape))
        for sp in sps
    ]
    buffers = [
        place_buffers(
            _leaf_arrays(child), dtype, split_complex, devices[mapping.device(i)]
        )
        for i, child in enumerate(children)
    ]

    def make_local_fn(sp):
        def fn(bufs, indices):
            if split_complex:
                sliced = [
                    (
                        index_buffer(jnp, re, info, indices),
                        index_buffer(jnp, im, info, indices),
                    )
                    for (re, im), info in zip(bufs, sp.slot_slices)
                ]
                return run_steps_split(
                    jnp, sp.program, sliced, precision,
                    policy=plan_kernels(sp.program),
                )
            sliced = [
                index_buffer(jnp, arr, info, indices)
                for arr, info in zip(bufs, sp.slot_slices)
            ]
            return _run_steps(jnp, sp.program, list(sliced))

        return jax.jit(fn)

    local_fns = [make_local_fn(sp) for sp in sps]

    # fan-in pair programs are slice-independent (legs already reduced)
    pair_programs = []
    pair_metas = list(metas)
    for x, y in contract_path.toplevel:
        program, result_meta = _pair_program(pair_metas[x], pair_metas[y])
        pair_programs.append(program)
        pair_metas[x] = result_meta
    root = (
        _fanin_survivor(k, contract_path.toplevel)
        if contract_path.toplevel
        else 0
    )
    final_meta = pair_metas[root]

    def run(max_slices: int | None = None):
        num = slicing.num_slices if max_slices is None else min(
            slicing.num_slices, max_slices
        )
        with obs.maybe_jax_profiler_trace(), obs.span(
            "partitioned.sliced_run", slices=num, partitions=k
        ):
            acc = _run_slices(num)

        if split_complex:
            from tnc_tpu.ops.split_complex import combine_array

            data = combine_array(*acc)
        else:
            data = np.asarray(acc)
        return data.reshape(tuple(final_meta.bond_dims))

    def _run_slices(num: int):
        acc = None
        for s in range(num):
            # host (uncommitted) indices: each jit transfers them to its
            # own partition's device
            indices = np.asarray(_slice_indices(slicing, s), dtype=np.int32)
            held = [
                fn(bufs, indices) for fn, bufs in zip(local_fns, buffers)
            ]  # async: all devices work concurrently
            for pi, (x, y) in enumerate(contract_path.toplevel):
                target = devices[mapping.device(x)]
                moved = jax.device_put(held[y], target)
                pair_fn = jit_program(
                    pair_programs[pi], split_complex, precision, donate=False
                )
                held[x] = pair_fn([held[x], moved])
                held[y] = None
            if acc is None:
                acc = held[root]
            elif split_complex:
                acc = (acc[0] + held[root][0], acc[1] + held[root][1])
            else:
                acc = acc + held[root]
        return acc

    return run, slicing, final_meta


# process-local counter giving every broadcast_object call a unique,
# deterministic KV key. broadcast_object is a collective: all processes
# call it the same number of times in the same order, so their counters
# agree by construction.
_KV_BCAST_SEQ = 0
_KV_BCAST_TIMEOUT_MS = 120_000


def _coordination_client():
    """The jax distributed coordination-service client (the same TCP
    channel ``jax.distributed.initialize`` already established), or
    ``None`` when unavailable (old jaxlib, or no distributed runtime).
    Private-API access is isolated here on purpose."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — any API drift → collective fallback
        return None


def broadcast_object(obj, root: int = 0):
    """Broadcast any picklable object from host process ``root`` to all
    processes — the generic transport under :func:`broadcast_path` and
    the cross-process fan-in (the reference's serialized MPI broadcast,
    ``mpi/communication.rs:14-28``).

    Identity when running single-process; non-root processes pass any
    value (it is ignored) and receive root's object.

    Transport: the distributed **coordination-service KV store** (root
    ``key_value_set``s the pickled payload under a per-call sequence
    key; everyone else blocks on it) — control-plane metadata rides the
    same reliable TCP channel ``jax.distributed.initialize`` set up,
    not the accelerator data plane. The previous transport
    (``multihost_utils.broadcast_one_to_all``, a device psum) was
    observed to silently return ZEROS for the payload phase on
    oversubscribed CPU/gloo test clusters — a corrupted path, not an
    error — which is exactly the failure mode a control channel must
    not have. The collective path is kept as a verified fallback for
    environments without a coordination client.
    """
    import jax

    if jax.process_count() == 1:
        return obj

    import pickle

    global _KV_BCAST_SEQ
    is_root = jax.process_index() == root

    client = _coordination_client()
    if client is not None:
        import base64

        seq = _KV_BCAST_SEQ
        _KV_BCAST_SEQ += 1
        key = f"tnc_tpu/bcast/{root}/{seq}"
        if is_root:
            client.key_value_set(
                key, base64.b64encode(pickle.dumps(obj)).decode("ascii")
            )
        blob = client.blocking_key_value_get(key, _KV_BCAST_TIMEOUT_MS)
        out = pickle.loads(base64.b64decode(blob))
        # reclaim the key: a barrier proves every process has read it,
        # then the root deletes — without this, a long-running job's
        # pickled payloads accumulate in the coordination service
        # forever. Best-effort: on any barrier/delete hiccup the key
        # simply stays resident (leak-not-break).
        try:
            client.wait_at_barrier(
                f"tnc_tpu/bcast_done/{root}/{seq}", _KV_BCAST_TIMEOUT_MS
            )
            if is_root:
                client.key_value_delete(key)
        except Exception:  # noqa: BLE001 — cleanup must never fail a bcast
            logger.debug("bcast key cleanup skipped for %s", key)
        return out

    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj) if is_root else b""
    # length-prefix phase (the reference broadcasts the length first)
    length = int(
        multihost_utils.broadcast_one_to_all(
            np.int64(len(payload)), is_source=is_root
        )
    )
    buf = np.frombuffer(payload.ljust(length, b"\0"), dtype=np.uint8)
    data = multihost_utils.broadcast_one_to_all(buf, is_source=is_root)
    raw = np.asarray(data).tobytes()
    try:
        return pickle.loads(raw)
    except Exception as exc:
        # turn the silent-zeros corruption mode into a diagnosable error
        raise RuntimeError(
            "collective object broadcast returned a corrupt payload "
            f"({len(raw)} bytes, {sum(b != 0 for b in raw[:64])} non-zero "
            "of the first 64) — the CPU/gloo collective backend on this "
            "host is unreliable; jax's coordination-service client was "
            "unavailable for the KV fallback"
        ) from exc


def broadcast_path(path_: ContractionPath, root: int = 0) -> ContractionPath:
    """Share the planner's path with every host process
    (``broadcast_path``, ``communication.rs:32-49``).

    Under JAX's single-controller model a single process plans and
    executes, so this is the identity; in a multi-process run
    (``jax.distributed.initialize``) the path found by the ``root``
    process is broadcast to all others as serialized bytes over the
    global mesh, the analogue of the reference's two-phase MPI vec
    broadcast (``communication.rs:14-28``).
    """
    return broadcast_object(path_, root=root)


# Reference-named aliases (``mpi/communication.rs:125,199``): the TPU
# executor's scatter/reduce are the same pipeline stages under the
# device-mesh model.
scatter_tensor_network = scatter_partitions
intermediate_reduce_tensor_network = intermediate_reduce
# the reference's generic serialized broadcast (``broadcast_serializing``,
# ``mpi/communication.rs:14-28``) — any picklable object from root to all
broadcast_serializing = broadcast_object
