"""ApproxProgram: serving workloads mapped onto the boundary-MPS
contractor.

The boundary contractor (:mod:`tnc_tpu.tensornetwork.approximate`)
consumes a closed 2-D grid of leaf tensors. This module flattens the
two serving workload families into that shape, with **rebindable leaf
sites** so per-request payloads swap leaf *data* without rebuilding the
grid — the same build-structure-once / rebind-per-request contract as
:mod:`tnc_tpu.serve.rebind`:

- **2-D lattices**: a ``builders.peps`` sandwich through the existing
  :func:`~tnc_tpu.tensornetwork.approximate.collapse_peps_sandwich`
  (:meth:`ApproxProgram.from_peps_sandwich`);
- **nearest-neighbour circuits** (line/brickwork): the amplitude
  network ⟨b|C|0⟩ flattened into a ``(depth+2) × qubits`` grid
  (:func:`circuit_to_grid` — ket row, one row per gate moment with
  two-qubit gates SVD-split across a horizontal bond, rebindable bra
  row), and the sandwich ⟨0|C†·O·C|0⟩ flattened into a
  ``(2·depth+3) × qubits`` grid (:func:`sandwich_to_grid` — ket layer,
  a rebindable per-qubit operator row, mirrored conjugate layer) which
  serves Pauli expectation values (operator row = Pauli matrices) and
  marginal probabilities (operator row = projectors / identities) from
  ONE grid for every request.

``chi`` at least the grid's exact boundary rank
(:func:`tnc_tpu.approx.cost.exact_chi_bound`) makes every answer exact;
below it the :mod:`tnc_tpu.approx.ladder` chi-ladder supplies the error
estimate.

>>> from tnc_tpu.builders.circuit_builder import Circuit
>>> from tnc_tpu.tensornetwork.tensordata import TensorData
>>> c = Circuit(); reg = c.allocate_register(2)
>>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
>>> c.append_gate(TensorData.gate("cx"), [reg.qubit(0), reg.qubit(1)])
>>> prog = ApproxProgram.from_circuit(c)   # c is read, not consumed
>>> value, weight = prog.rebind_bits("11").contract(chi=4)
>>> round(abs(value), 6), weight           # Bell state: 1/sqrt(2), exact
(0.707107, 0.0)
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from tnc_tpu.builders.circuit_builder import (
    BASIS_STATES,
    PAULI_MATRICES,
    Circuit,
    normalize_bitstring,
    observable_leaf_data,
)
from tnc_tpu.tensornetwork.approximate import boundary_contract_with_weight
from tnc_tpu.tensornetwork.tensor import LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData

__all__ = [
    "ApproxProgram",
    "circuit_to_grid",
    "sandwich_to_grid",
]

#: one-hot projectors |0⟩⟨0| / |1⟩⟨1| for marginal operator rows
_PROJECTORS = {
    "0": np.diag([1.0 + 0.0j, 0.0 + 0.0j]),
    "1": np.diag([0.0 + 0.0j, 1.0 + 0.0j]),
}


def _leaf(legs: Sequence[int], dims: Sequence[int], arr) -> LeafTensor:
    return LeafTensor(
        list(legs),
        list(dims),
        TensorData.matrix(np.asarray(arr, dtype=np.complex128)),
    )


def _circuit_ops(circuit: Circuit):
    """Replay the builder's tensor list (kets then gates, the
    :mod:`tnc_tpu.queries.statevector` discipline) into
    ``(num_qubits, [(qubit tuple, gate array), ...])`` without
    consuming the circuit."""
    if circuit._finalized:
        raise ValueError(
            "approx programs need an un-finalized circuit (copy before "
            "calling a finalizer)"
        )
    n = circuit.num_qubits()
    edge_qubit: dict[int, int] = {}
    next_ket = 0
    ops: list[tuple[tuple[int, ...], np.ndarray]] = []
    for tensor in circuit.tensor_network.tensors:
        legs = list(tensor.legs)
        if len(legs) == 1:  # an initial |0⟩ ket
            edge_qubit[legs[0]] = next_ket
            next_ket += 1
            continue
        k = len(legs) // 2
        if k > 2:
            raise ValueError(
                f"approx grids support 1- and 2-qubit gates; got a "
                f"{k}-qubit gate"
            )
        new, old = legs[:k], legs[k:]
        qubits = tuple(edge_qubit[e] for e in old)
        for e, q in zip(new, qubits):
            edge_qubit[e] = q
        arr = np.asarray(
            tensor.data.into_data(), dtype=np.complex128
        ).reshape((2,) * (2 * k))
        ops.append((qubits, arr))
    return n, ops


def _split_two_qubit(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """SVD-split a two-qubit gate ``G[n0, n1, o0, o1]`` into site
    halves ``A[n0, o0, r]`` / ``B[r, n1, o1]`` over a horizontal bond
    of the gate's numerical operator-Schmidt rank (CX: 2)."""
    m = np.transpose(arr, (0, 2, 1, 3)).reshape(4, 4)
    u, s, vh = np.linalg.svd(m)
    keep = max(1, int(np.sum(s > (s[0] if s.size else 1.0) * 1e-13)))
    root = np.sqrt(s[:keep])
    a = (u[:, :keep] * root).reshape(2, 2, keep)
    b = (root[:, None] * vh[:keep]).reshape(keep, 2, 2)
    return a, b


def _schedule_moments(n: int, ops) -> list[dict]:
    """ASAP-schedule gates into moments (rows of the grid). Each moment
    maps column → ``("one", arr)`` or the ``("left", A)`` /
    ``("right", B)`` halves of a split nearest-neighbour gate."""
    avail = [0] * n
    moments: list[dict] = []
    for qubits, arr in ops:
        row = max(avail[q] for q in qubits)
        while len(moments) <= row:
            moments.append({})
        if len(qubits) == 1:
            moments[row][qubits[0]] = ("one", arr)
        else:
            q0, q1 = qubits
            if abs(q0 - q1) != 1:
                raise ValueError(
                    f"the approx tier flattens nearest-neighbour "
                    f"circuits only; a gate acts on non-adjacent qubits "
                    f"{(q0, q1)}"
                )
            if q0 > q1:  # reorder legs so axis 0 is the lower column
                arr = np.transpose(arr, (1, 0, 3, 2))
                q0, q1 = q1, q0
            a, b = _split_two_qubit(arr)
            moments[row][q0] = ("left", a)
            moments[row][q1] = ("right", b)
        for q in qubits:
            avail[q] = row + 1
    return moments


def _moment_row(
    moment: dict, wires: list[int], legno, conj: bool = False
) -> tuple[list[LeafTensor], list[int]]:
    """One grid row for a gate moment. ``wires`` are the incoming wire
    legs (from the row above); returns the row and the outgoing wires.
    ``conj=True`` builds the adjoint-mirror layer's version:
    complex-conjugated data with the wire ROLES mirrored — in the ket
    layer a gate's new (output) axis faces down the grid, in the conj
    layer it faces UP (toward the operator row), because the mirror
    computes conj(ψ)_b = Σ_i conj(G)[b, i] ket_i with b on top.
    Binding conj data with unchanged orientation would transpose every
    gate, which is invisible for symmetric gates (h/rz/cx) but wrong
    for anything else (ry, sy, ...)."""
    n = len(wires)
    row: list[LeafTensor] = []
    out_wires = list(wires)
    hlegs: dict[int, int] = {}

    def data(arr):
        return np.conj(arr) if conj else arr

    for q in range(n):
        win = wires[q]
        wout = next(legno)
        out_wires[q] = wout
        # the leg carrying the gate's NEW (output) axis vs its OLD
        # (input) axis; data arrays are stored [new..., old...]
        new_leg, old_leg = (win, wout) if conj else (wout, win)
        entry = moment.get(q)
        if entry is None:
            row.append(_leaf([new_leg, old_leg], [2, 2], np.eye(2)))
        elif entry[0] == "one":
            row.append(_leaf([new_leg, old_leg], [2, 2], data(entry[1])))
        elif entry[0] == "left":
            a = entry[1]  # [n0, o0, r]
            h = next(legno)
            hlegs[q] = h
            row.append(
                _leaf([new_leg, old_leg, h], [2, 2, a.shape[2]], data(a))
            )
        else:  # "right" — its "left" partner is column q-1
            b = entry[1]  # [r, n1, o1]
            row.append(
                _leaf(
                    [hlegs[q - 1], new_leg, old_leg],
                    [b.shape[0], 2, 2],
                    data(b),
                )
            )
    return row, out_wires


def circuit_to_grid(
    circuit: Circuit,
) -> tuple[list[list[LeafTensor]], list[LeafTensor]]:
    """Flatten a nearest-neighbour circuit's amplitude network
    ⟨b|C|0⟩ into the ``(moments+2) × qubits`` grid the boundary
    contractor consumes. Returns ``(grid, bras)`` — ``bras`` are the
    bottom-row leaves in qubit order, initialized to ⟨0| and rebindable
    per request (:meth:`ApproxProgram.rebind_bits`). The circuit is
    read, not consumed."""
    n, ops = _circuit_ops(circuit)
    if n < 1:
        raise ValueError("circuit has no qubits")
    moments = _schedule_moments(n, ops)
    legno = itertools.count()
    wires = [next(legno) for _ in range(n)]
    grid: list[list[LeafTensor]] = [
        [_leaf([wires[q]], [2], BASIS_STATES["0"]) for q in range(n)]
    ]
    for moment in moments:
        row, wires = _moment_row(moment, wires, legno)
        grid.append(row)
    bras = [_leaf([wires[q]], [2], BASIS_STATES["0"]) for q in range(n)]
    grid.append(bras)
    return grid, bras


def sandwich_to_grid(
    circuit: Circuit,
) -> tuple[list[list[LeafTensor]], list[LeafTensor]]:
    """Flatten the sandwich ⟨0|C† (O₁⊗…⊗Oₙ) C|0⟩ of a
    nearest-neighbour circuit into a ``(2·moments+3) × qubits`` grid:
    ket row, the circuit's moment rows, ONE per-qubit operator row
    (legs ``[ket wire, conj wire]``, data stored transposed via
    :func:`~tnc_tpu.builders.circuit_builder.observable_leaf_data` so
    the grid value is ⟨ψ|O|ψ⟩), the conjugated moment rows mirrored in
    reverse order, and a closing ⟨0| row. Returns ``(grid, op_leaves)``
    — the operator leaves in qubit order, initialized to the identity
    and rebindable per request (Pauli strings for expectation values,
    projectors for marginal probabilities). The circuit is read, not
    consumed."""
    n, ops = _circuit_ops(circuit)
    if n < 1:
        raise ValueError("circuit has no qubits")
    moments = _schedule_moments(n, ops)
    legno = itertools.count()
    wires = [next(legno) for _ in range(n)]
    grid: list[list[LeafTensor]] = [
        [_leaf([wires[q]], [2], BASIS_STATES["0"]) for q in range(n)]
    ]
    for moment in moments:
        row, wires = _moment_row(moment, wires, legno)
        grid.append(row)
    conj_wires = [next(legno) for _ in range(n)]
    op_leaves = [
        LeafTensor(
            [wires[q], conj_wires[q]],
            [2, 2],
            observable_leaf_data(PAULI_MATRICES["i"]),
        )
        for q in range(n)
    ]
    grid.append(op_leaves)
    wires = conj_wires
    for moment in reversed(moments):
        row, wires = _moment_row(moment, wires, legno, conj=True)
        grid.append(row)
    grid.append(
        [_leaf([wires[q]], [2], BASIS_STATES["0"]) for q in range(n)]
    )
    return grid, op_leaves


@dataclass
class ApproxProgram:
    """A serving workload bound to a boundary-MPS grid.

    Built once per circuit / lattice *structure*; per-request payloads
    rebind leaf data in place (the grid, its leg structure, and the
    per-(shapes, chi) compiled row steps are all payload-independent),
    then :meth:`contract` runs one sweep at a given ``chi`` and returns
    ``(value, discarded_weight)``.
    """

    grid: list[list[LeafTensor]]
    kind: str  # "amplitude" | "sandwich" | "value"
    num_qubits: int = 0
    rebind_sites: tuple[LeafTensor, ...] = ()
    cutoff: float = 0.0
    _dims: list = field(default=None, repr=False, compare=False)
    _costs: dict = field(default_factory=dict, repr=False, compare=False)

    @classmethod
    def from_circuit(cls, circuit: Circuit) -> "ApproxProgram":
        """Amplitude program ⟨b|C|0⟩ with rebindable bras
        (nearest-neighbour circuits; the circuit is read, not
        consumed)."""
        grid, bras = circuit_to_grid(circuit)
        return cls(
            grid=grid,
            kind="amplitude",
            num_qubits=circuit.num_qubits(),
            rebind_sites=tuple(bras),
        )

    @classmethod
    def sandwich_from_circuit(cls, circuit: Circuit) -> "ApproxProgram":
        """Sandwich program ⟨ψ|O₁⊗…⊗Oₙ|ψ⟩ with a rebindable operator
        row — expectation values and marginal probabilities share this
        ONE grid."""
        grid, op_leaves = sandwich_to_grid(circuit)
        return cls(
            grid=grid,
            kind="sandwich",
            num_qubits=circuit.num_qubits(),
            rebind_sites=tuple(op_leaves),
        )

    @classmethod
    def from_peps_sandwich(
        cls, tn, length: int, depth: int, layers: int
    ) -> "ApproxProgram":
        """Closed-value program over a ``builders.peps`` sandwich (data
        attached); no rebindable sites — each contraction answers the
        one scalar the lattice defines."""
        from tnc_tpu.tensornetwork.approximate import collapse_peps_sandwich

        grid = collapse_peps_sandwich(tn, length, depth, layers)
        return cls(grid=grid, kind="value")

    # -- rebinding ---------------------------------------------------------

    def rebind_bits(self, bits: str | Iterable) -> "ApproxProgram":
        """Swap the bra row to ⟨bits| (amplitude programs). Fully
        determined bitstrings only — the boundary sweep computes one
        scalar."""
        if self.kind != "amplitude":
            raise ValueError(
                f"rebind_bits applies to amplitude programs, not "
                f"{self.kind!r}"
            )
        bits = normalize_bitstring(bits, self.num_qubits)
        if "*" in bits:
            raise ValueError(
                "approx amplitude requests must be fully determined "
                "(no '*' positions)"
            )
        for leaf, c in zip(self.rebind_sites, bits):
            leaf.data = TensorData.matrix(BASIS_STATES[c].copy())
        return self

    def rebind_operators(self, mats: Sequence) -> "ApproxProgram":
        """Swap the operator row (sandwich programs): one 2×2 operator
        per qubit, ``None`` = identity."""
        if self.kind != "sandwich":
            raise ValueError(
                f"rebind_operators applies to sandwich programs, not "
                f"{self.kind!r}"
            )
        mats = list(mats)
        if len(mats) != self.num_qubits:
            raise ValueError(
                f"expected {self.num_qubits} operators, got {len(mats)}"
            )
        for q, (leaf, m) in enumerate(zip(self.rebind_sites, mats)):
            m = PAULI_MATRICES["i"] if m is None else np.asarray(m)
            if m.shape != (2, 2):
                raise ValueError(
                    f"operator for qubit {q} must be 2x2, got {m.shape}"
                )
            leaf.data = observable_leaf_data(m)
        return self

    def rebind_pauli(self, pauli: str) -> "ApproxProgram":
        """Operator row ← a Pauli string (one of ``ixyz`` per qubit)."""
        from tnc_tpu.queries.statevector import normalize_pauli

        pauli = normalize_pauli(pauli, self.num_qubits)
        return self.rebind_operators([PAULI_MATRICES[c] for c in pauli])

    def rebind_projectors(self, pattern: str | Iterable) -> "ApproxProgram":
        """Operator row ← the marginal projector of ``pattern``
        (``'0'``/``'1'`` = |b⟩⟨b|, ``'*'`` = identity); the grid value
        becomes the marginal probability of the determined bits."""
        pattern = normalize_bitstring(pattern, self.num_qubits)
        return self.rebind_operators(
            [None if c == "*" else _PROJECTORS[c] for c in pattern]
        )

    # -- execution ---------------------------------------------------------

    def contract(
        self, chi: int, backend: str = "numpy"
    ) -> tuple[complex, float]:
        """One boundary sweep at ``chi``: ``(value, discarded
        weight)``."""
        return boundary_contract_with_weight(
            self.grid, chi, cutoff=self.cutoff, backend=backend
        )

    def site_dims(self):
        """Cached grid geometry for the closed-form cost model."""
        if self._dims is None:
            from tnc_tpu.tensornetwork.approximate import grid_site_dims

            self._dims = grid_site_dims(self.grid)
        return self._dims

    def sweep_cost(self, chi: int):
        """Memoized closed-form sweep cost at ``chi`` — rebinding swaps
        leaf data, never geometry, so one walk per ``chi`` serves every
        request and stats scrape (the serving hot path prices rungs per
        request, and ``/metrics`` re-quotes per scrape)."""
        cost = self._costs.get(chi)
        if cost is None:
            from tnc_tpu.approx.cost import sweep_cost

            cost = sweep_cost(self.site_dims(), chi)
            self._costs[chi] = cost
        return cost
