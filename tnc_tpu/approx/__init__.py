"""tnc_tpu.approx — the fidelity-tiered approximate serving tier.

Most traffic does not need an exact sycamore-class contraction; it
needs a cheap answer with an honest error bar. This package promotes
the boundary-MPS contractor
(:mod:`tnc_tpu.tensornetwork.approximate`) into that serving tier:

- :class:`ApproxProgram` (``program.py``) — serving workloads mapped
  onto the boundary contractor: PEPS sandwiches via
  ``collapse_peps_sandwich``, nearest-neighbour circuit amplitudes and
  expectation/marginal sandwiches flattened into qubit×depth grids,
  all with rebindable leaf sites (per-request payloads swap leaf data
  without rebuilding the grid — the ``serve/rebind`` contract).
- :class:`ChiLadder` (``ladder.py``) — runs a request at ascending
  ``chi`` rungs, derives a per-answer error estimate from discarded
  SVD weight plus inter-rung deltas, and reports
  ``(value, err, chi_used)``; converged answers stop climbing,
  unconverged ones escalate.
- ``cost.py`` — closed-form flop/byte pricing of every rung through
  :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`, so admission
  control quotes approximate-tier latency exactly like exact plans.

The service front end (:class:`tnc_tpu.serve.service.FidelityRouter`)
routes ``rtol=``-tolerant requests here and escalates misses to the
exact pipeline. See ``docs/approximate.md``.
"""

from tnc_tpu.approx.cost import (  # noqa: F401
    SweepCost,
    default_chis,
    exact_chi_bound,
    ladder_seconds,
    rung_seconds,
    sweep_cost,
)
from tnc_tpu.approx.ladder import (  # noqa: F401
    ChiLadder,
    LadderResult,
    Rung,
)
from tnc_tpu.approx.program import (  # noqa: F401
    ApproxProgram,
    circuit_to_grid,
    sandwich_to_grid,
)
