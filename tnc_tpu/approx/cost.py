"""Closed-form pricing of boundary-MPS sweeps.

Admission control quotes latency for the exact tier from a plan's step
flops through :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`; this
module gives the approximate tier the same treatment. A sweep's cost is
a pure function of the grid geometry and ``chi`` — no site data, no
trial contraction: :func:`sweep_cost` walks the boundary shapes row by
row through the SAME counting helpers the live sweep attaches to its
``approx.row`` spans (:func:`tnc_tpu.tensornetwork.approximate.
row_cost`), so predicted and measured rows line up one-to-one in a
trace.

:func:`exact_chi_bound` is the geometry's exact boundary rank bound —
the ``chi`` above which truncation cannot happen — and
:func:`default_chis` turns it into the ladder's doubling rung schedule.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from tnc_tpu.tensornetwork.approximate import (
    close_cost,
    grid_site_dims,
    row_cost,
)

__all__ = [
    "SweepCost",
    "default_chis",
    "exact_chi_bound",
    "ladder_seconds",
    "rung_seconds",
    "sweep_cost",
]

#: clamp for bond-dim products (anything above is "unreachably large")
_DIM_CAP = 1 << 62


def _dims_of(grid_or_dims):
    """Accept a grid of leaf tensors, an :class:`~tnc_tpu.approx.
    program.ApproxProgram`, or a precomputed ``grid_site_dims``
    result."""
    site_dims = getattr(grid_or_dims, "site_dims", None)
    if callable(site_dims):
        return site_dims()
    if (
        grid_or_dims
        and grid_or_dims[0]
        and isinstance(grid_or_dims[0][0], tuple)
    ):
        return grid_or_dims
    return grid_site_dims(grid_or_dims)


@dataclass(frozen=True)
class SweepCost:
    """One sweep's predicted totals plus the per-row breakdown
    (``rows[i] = (flops, bytes, ops)`` for interior row ``i+1``; the
    final entry is the bottom-row close)."""

    flops: float
    nbytes: float
    ops: int
    rows: tuple[tuple[float, float, int], ...]


def sweep_cost(grid_or_dims, chi: int) -> SweepCost:
    """Closed-form cost of one boundary sweep at ``chi``."""
    dims = _dims_of(grid_or_dims)
    if chi < 1:
        raise ValueError("chi must be >= 1")
    mps = [(l, d, r) for (l, r, _u, d) in dims[0]]
    rows: list[tuple[float, float, int]] = []
    flops = nbytes = 0.0
    ops = 0
    for row in dims[1:-1]:
        mpo = [(l, r, u, d) for (l, r, u, d) in row]
        f, b, o, mps = row_cost(mps, mpo, chi)
        rows.append((f, b, o))
        flops += f
        nbytes += b
        ops += o
    bottom = [(l, u, r) for (l, r, u, _d) in dims[-1]]
    f, b, o = close_cost(mps, bottom)
    rows.append((f, b, o))
    return SweepCost(flops + f, nbytes + b, ops + o, tuple(rows))


def rung_seconds(grid_or_dims, chi: int, cost_model) -> float:
    """Predicted seconds of ONE sweep at ``chi`` under a
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` — the unit
    admission control quotes per ladder rung. An
    :class:`~tnc_tpu.approx.program.ApproxProgram` answers from its
    per-``chi`` memo (geometry is frozen; only leaf data rebinds)."""
    memo = getattr(grid_or_dims, "sweep_cost", None)
    cost = memo(chi) if callable(memo) else sweep_cost(grid_or_dims, chi)
    return cost_model.op_seconds(
        cost.flops, cost.nbytes, dispatches=max(cost.ops, 1)
    )


def ladder_seconds(
    grid_or_dims, chis: Sequence[int], cost_model
) -> float:
    """Predicted seconds of a full ladder climb (the worst case a
    tolerant request can cost before converging or escalating)."""
    return float(
        sum(rung_seconds(grid_or_dims, chi, cost_model) for chi in chis)
    )


def exact_chi_bound(grid_or_dims, cap: int = _DIM_CAP) -> int:
    """The geometry's exact boundary rank bound: the smallest ``chi``
    at which no sweep truncation can discard weight. For each boundary
    (rows ``0..r`` absorbed) and each vertical cut, the rank is bounded
    by the smaller of the open (downward) dims on either side and the
    product of horizontal bonds crossing the cut; the bound is the max
    over boundaries and cuts, clamped to ``cap``."""
    dims = _dims_of(grid_or_dims)
    cols = len(dims[0])
    if cols < 2:
        return 1
    best = 1
    hprod = [1] * (cols - 1)
    for row in dims[:-1]:
        for c in range(cols - 1):
            hprod[c] = min(hprod[c] * row[c][1], cap)  # right-dim
        left = 1
        down = [site[3] for site in row]
        total = 1
        for d in down:
            total = min(total * d, cap)
        for c in range(cols - 1):
            left = min(left * down[c], cap)
            right = max(total // max(left, 1), 1)
            best = max(best, min(left, right, hprod[c]))
            if best >= cap:
                return cap
    return best


def default_chis(
    grid_or_dims, chi_start: int = 2, chi_cap: int = 64
) -> tuple[int, ...]:
    """The ladder's default rung schedule: double from ``chi_start``
    up to ``min(exact_chi_bound, chi_cap)``, always ending on that
    bound — so when the exact rank fits under the cap the top rung is
    truncation-free and every tolerance converges.

    >>> import numpy as np
    >>> from tnc_tpu.builders.peps import peps
    >>> from tnc_tpu.tensornetwork.approximate import (
    ...     attach_random_data, collapse_peps_sandwich)
    >>> tn = attach_random_data(peps(4, 4, 2, 2, 0),
    ...                         np.random.default_rng(0))
    >>> grid = collapse_peps_sandwich(tn, 4, 4, 0)
    >>> default_chis(grid)
    (2, 4, 8, 16)
    """
    bound = exact_chi_bound(_dims_of(grid_or_dims))
    top = min(bound, chi_cap)
    chis = []
    chi = min(chi_start, top)
    while chi < top:
        chis.append(chi)
        chi *= 2
    chis.append(top)
    return tuple(chis)
