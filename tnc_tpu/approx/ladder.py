"""Chi-ladder execution: ascending-``chi`` sweeps with per-answer
error estimates.

A request runs at ascending ``chi`` rungs; each rung's sweep reports
its accumulated relative discarded SVD weight
(:func:`~tnc_tpu.tensornetwork.approximate.boundary_contract_with_weight`),
and the ladder derives an **error estimate** per rung:

- weight ≤ :data:`~tnc_tpu.tensornetwork.approximate.EXACT_WEIGHT`:
  nothing was truncated — the sweep is the exact contraction up to
  roundoff, ``err = fp_floor · max(|v|, scale)`` where ``fp_floor`` is
  the executing backend's precision (:data:`EXACT_ERR_REL` for
  complex128, :data:`COMPLEX64_ERR_REL` for a single-precision jax
  sweep — a float32 sweep must never claim a float64 bar); every
  finite estimate below is floored by the same term;
- first truncated rung: ``err = inf`` — a single truncated sweep
  carries no convergence evidence, so the estimate refuses to vouch
  for it (the ladder always climbs at least one more rung);
- later rungs: ``err = safety · (|v_k − v_{k−1}| +
  max(|v_k|, scale) · √weight_k)`` — the observed inter-rung movement
  plus the truncation-weight bound on the state error, inflated by
  ``safety``. The weight term scales with ``max(|v|, scale)``: under
  heavy truncation the approximate value itself can collapse toward
  zero, and an error bar proportional to the collapsed value would
  vouch for exactly the answers it should distrust.

Convergence: ``err ≤ rtol · max(|v|, scale)`` — ``scale`` anchors the
tolerance for answers whose magnitude is legitimately tiny (an
amplitude's natural scale is ``2^(-n/2)``, a probability's is 1).
Converged answers stop climbing; a ladder that exhausts its rungs
without converging reports ``converged=False`` and the serving router
escalates to the exact pipeline
(:class:`tnc_tpu.serve.service.FidelityRouter`).

>>> from tnc_tpu.approx.program import ApproxProgram
>>> from tnc_tpu.builders.circuit_builder import Circuit
>>> from tnc_tpu.tensornetwork.tensordata import TensorData
>>> c = Circuit(); reg = c.allocate_register(2)
>>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
>>> c.append_gate(TensorData.gate("cx"), [reg.qubit(0), reg.qubit(1)])
>>> res = ChiLadder().run(ApproxProgram.from_circuit(c).rebind_bits("00"),
...                       rtol=1e-6, scale=0.5)
>>> res.converged, res.chi_used, round(abs(res.value), 6)
(True, 2, 0.707107)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

from tnc_tpu import obs
from tnc_tpu.tensornetwork.approximate import EXACT_WEIGHT

__all__ = [
    "ChiLadder", "LadderResult", "Rung",
    "COMPLEX64_ERR_REL", "EXACT_ERR_REL",
]

#: relative error attributed to an untruncated (exact) complex128
#: sweep — pure floating-point margin vs a differently-ordered exact
#: contraction
EXACT_ERR_REL = 1e-9

#: the same margin when the sweep ran in single precision (the jax
#: backend without ``jax_enable_x64``): unit roundoff ~1e-7 compounds
#: over the row products, so every rung's bar is floored here —
#: without it an untruncated complex64 sweep would claim a 1e-9 bar
#: while carrying ~1e-7-scale error (caught against the dense oracle)
COMPLEX64_ERR_REL = 1e-4


def _fp_floor(backend: str) -> float:
    """The sweep's floating-point error floor (relative) for the
    backend that will run it."""
    if backend == "jax":
        import jax

        if not jax.config.read("jax_enable_x64"):
            return COMPLEX64_ERR_REL
    return EXACT_ERR_REL


@dataclass(frozen=True)
class Rung:
    """One executed rung: the sweep's value, its accumulated discarded
    SVD weight, the derived error estimate, and (when a cost model
    priced the ladder) the rung's predicted seconds."""

    chi: int
    value: complex
    weight: float
    err: float
    predicted_s: float | None = None


@dataclass(frozen=True)
class LadderResult:
    """The ladder's answer: ``value`` with error estimate ``err`` at
    bond dimension ``chi_used``; ``converged`` says whether ``err`` met
    the requested tolerance (the router escalates when it didn't);
    ``rungs`` records the whole climb."""

    value: complex
    err: float
    chi_used: int
    converged: bool
    rungs: tuple[Rung, ...]

    @property
    def sweeps(self) -> int:
        return len(self.rungs)


class ChiLadder:
    """Run requests up a ``chi`` ladder until the error estimate meets
    the requested tolerance.

    ``chis`` pins the rungs explicitly; otherwise they double from
    ``chi_start`` up to ``min(exact boundary rank, chi_cap)`` per grid
    (:func:`tnc_tpu.approx.cost.default_chis`) — when the exact rank
    fits under the cap the top rung is truncation-free, so every
    tolerance converges; when it doesn't, tight tolerances can exhaust
    the ladder and escalate. ``safety`` inflates the error estimate
    (larger = more honest bars, more escalations).
    """

    def __init__(
        self,
        chis: Sequence[int] | None = None,
        chi_start: int = 2,
        chi_cap: int = 64,
        safety: float = 4.0,
    ) -> None:
        if chis is not None:
            chis = tuple(int(c) for c in chis)
            if not chis or any(c < 1 for c in chis):
                raise ValueError("chis must be a non-empty list of >= 1")
            if list(chis) != sorted(chis):
                raise ValueError("chis must ascend")
        if chi_start < 1 or chi_cap < chi_start:
            raise ValueError("need 1 <= chi_start <= chi_cap")
        if safety <= 0.0:
            raise ValueError("safety must be > 0")
        self.chis = chis
        self.chi_start = int(chi_start)
        self.chi_cap = int(chi_cap)
        self.safety = float(safety)

    def rungs_for(self, program) -> tuple[int, ...]:
        """The rung schedule for one program's grid."""
        if self.chis is not None:
            return self.chis
        from tnc_tpu.approx.cost import default_chis

        # pass the program, not its grid: the bound is derived from the
        # memoized site_dims geometry
        return default_chis(
            program, chi_start=self.chi_start, chi_cap=self.chi_cap
        )

    def estimate(
        self,
        value: complex,
        weight: float,
        prev: complex | None,
        scale: float = 0.0,
        fp_floor: float = EXACT_ERR_REL,
    ) -> float:
        """The per-rung error estimate (module docstring semantics).
        ``fp_floor`` is the executing backend's relative roundoff
        floor — every finite estimate is floored by it, so a
        single-precision sweep never claims a double-precision bar."""
        floor = fp_floor * max(abs(value), scale)
        if weight <= EXACT_WEIGHT:
            return floor
        if prev is None:
            return math.inf
        return floor + self.safety * (
            abs(value - prev) + max(abs(value), scale) * math.sqrt(weight)
        )

    def run(
        self,
        program,
        rtol: float,
        scale: float = 0.0,
        backend: str = "numpy",
        cost_model=None,
    ) -> LadderResult:
        """Climb the ladder for the program's CURRENT binding.

        ``rtol`` is relative to ``max(|value|, scale)``;
        ``cost_model`` (a
        :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`) prices
        each executed rung in predicted seconds on its
        :class:`Rung`."""
        if rtol <= 0.0:
            raise ValueError("rtol must be > 0")
        chis = self.rungs_for(program)
        fp_floor = _fp_floor(backend)
        rungs: list[Rung] = []
        prev: complex | None = None
        value, err, chi = 0.0 + 0.0j, math.inf, chis[0]
        with obs.span(
            "approx.ladder", rtol=rtol, max_rungs=len(chis),
            kind=program.kind,
        ) as sp:
            for chi in chis:
                predicted = None
                if cost_model is not None:
                    from tnc_tpu.approx.cost import rung_seconds

                    predicted = rung_seconds(program, chi, cost_model)
                value, weight = program.contract(chi, backend=backend)
                err = self.estimate(value, weight, prev, scale, fp_floor)
                rungs.append(Rung(chi, value, weight, err, predicted))
                if err <= rtol * max(abs(value), scale):
                    sp.add(rungs=len(rungs))
                    return LadderResult(value, err, chi, True, tuple(rungs))
                prev = value
            sp.add(rungs=len(rungs))
        return LadderResult(value, err, chi, False, tuple(rungs))
