"""Batched amplitude sweeps: many bitstrings through one compiled program.

The reference computes one amplitude per run (its benchmark re-enters
the whole pipeline per scenario, ``benchmark/src/main.rs``). On TPU the
natural shape is different: an amplitude network's *structure* is
bitstring-independent — only the ⟨0|/⟨1| bra leaf values change — so one
contraction path, one compiled XLA program, and a ``vmap`` over the
stacked bra values evaluate B amplitudes in a single device dispatch.
This is a capability layer the reference has no analogue for; it exists
because the network→program split (:mod:`tnc_tpu.ops.program`) makes
"same shapes, different values" a first-class case.

The sweep plans on the **raw** (unsimplified) network: host
simplification folds bra values into neighboring cores, which would make
the shared leaf arrays bitstring-dependent. Rank-≤2 absorption happens
inside the planned path instead (the hyper/greedy planners' preprocessing
does the same structurally), so the per-step work is equivalent while
every non-bra leaf stays bitstring-independent.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tnc_tpu.builders.circuit_builder import BASIS_STATES, Circuit
from tnc_tpu.contractionpath.paths.base import Pathfinder
from tnc_tpu.ops.program import build_program, flat_leaf_tensors

# the builder's canonical one-hot table (shared with serve/rebind.py so
# a dtype/layout change cannot skew sweep kets and serving bras apart)
_KET = BASIS_STATES


def _sweep_program(circuit, bitstrings, pathfinder):
    """Shared sweep prologue: validate bitstrings, build the amplitude
    network, plan, compile, and stack per-bitstring bra values.

    Returns ``(program, arrays, bra_slots)``; ``arrays[slot]`` for bra
    slots carries the stacked ``(B, 2)`` sweep axis. The finalizer
    pushes one bra per qubit, in qubit order, after every circuit
    tensor — they are the trailing ``n`` leaves.
    """
    n = len(bitstrings[0])
    for b in bitstrings:
        if len(b) != n:
            raise ValueError("all bitstrings must have equal length")
        if any(c not in "01" for c in b):
            raise ValueError(
                "the amplitude branch of a sweep requires fully "
                "determined bitstrings ('*' wildcards route to the "
                "marginal branch before this point)"
            )

    tn, _ = circuit.into_amplitude_network(bitstrings[0])
    leaves = flat_leaf_tensors(tn)
    bra_slots = list(range(len(leaves) - n, len(leaves)))

    if pathfinder is None:
        from tnc_tpu.contractionpath.paths import Greedy, OptMethod

        pathfinder = Greedy(OptMethod.GREEDY)
    result = pathfinder.find_path(tn)
    program = build_program(tn, result.replace_path())

    arrays = [leaf.data.into_data() for leaf in leaves]
    for qubit, slot in enumerate(bra_slots):
        arrays[slot] = np.stack([_KET[b[qubit]] for b in bitstrings])
    return program, arrays, bra_slots


def amplitude_sweep(
    circuit: Circuit,
    bitstrings: Sequence[str],
    pathfinder: Pathfinder | None = None,
    backend=None,
) -> np.ndarray:
    """Amplitudes ⟨b|C|0…0⟩ for every bitstring ``b``, sharing one path
    and one compiled program. Returns a complex ``(len(bitstrings),)``
    array in input order.

    ``circuit`` is consumed (finalizer semantics, like every
    ``into_*_network``). All bitstrings must be of equal length.

    **Wildcards**: a ``'*'`` position marginalizes that qubit — the
    sweep returns the real marginal *probabilities* of the determined
    positions (``Σ_wildcards |⟨b|C|0⟩|²``) instead of complex
    amplitudes, contracted as traced sandwich legs by
    :func:`tnc_tpu.queries.marginal.marginal_sweep`. All bitstrings of
    one sweep must then share the same wildcard mask (the mask IS the
    network structure; split per-mask to mix).

    >>> from tnc_tpu.builders.circuit_builder import Circuit as _C
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData as _T
    >>> c = _C(); reg = c.allocate_register(2)
    >>> c.append_gate(_T.gate("x"), [reg.qubit(0)])
    >>> amplitude_sweep(c, ["1*", "0*"]).tolist()
    [1.0, 0.0]

    >>> import math
    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(3)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> for i in range(2):
    ...     c.append_gate(TensorData.gate("cx"), [reg.qubit(i), reg.qubit(i + 1)])
    >>> amps = amplitude_sweep(c, ["000", "111", "010"])
    >>> [round(abs(a), 6) for a in amps] == [
    ...     round(1 / math.sqrt(2), 6), round(1 / math.sqrt(2), 6), 0.0]
    True
    """
    if not bitstrings:
        return np.zeros((0,), dtype=np.complex128)
    if any("*" in str(b) for b in bitstrings):
        # wildcard sweep = marginal probabilities over the sandwich
        # network (lazy import: queries builds on the serve layer,
        # which imports this module's package)
        from tnc_tpu.queries.marginal import marginal_sweep

        return marginal_sweep(
            circuit, list(bitstrings), pathfinder=pathfinder,
            backend=backend,
        )
    program, arrays, bra_slots = _sweep_program(
        circuit, bitstrings, pathfinder
    )

    if backend is None:
        from tnc_tpu.ops.backends import JaxBackend

        backend = JaxBackend(dtype="complex64")
    if hasattr(backend, "execute_batched"):
        out = backend.execute_batched(program, arrays, bra_slots)
        return np.asarray(out).reshape(len(bitstrings))

    # host oracle / generic backend: loop (same result, B dispatches)
    out = np.zeros((len(bitstrings),), dtype=np.complex128)
    bra_set = set(bra_slots)
    for i in range(len(bitstrings)):
        per = [
            a[i] if slot in bra_set else a for slot, a in enumerate(arrays)
        ]
        out[i] = complex(np.asarray(backend.execute(program, per)).reshape(-1)[0])
    return out


def amplitude_sweep_value_and_grad(
    circuit: Circuit,
    bitstrings: Sequence[str],
    wrt: Sequence[int] | None = None,
    scalar_fn=None,
    pathfinder: Pathfinder | None = None,
    dtype: str = "complex64",
):
    """Amplitudes for every bitstring AND the gradient of a real scalar
    of them w.r.t. selected (non-bra) leaf tensors — one reverse-mode
    sweep through the same vmapped program the forward sweep runs
    (closing the "gradients of amplitude sweeps" half of
    docs/future_work.md item 4). The natural loss for sampling-based
    training is the default ``scalar_fn``: total probability mass
    ``sum |amp_b|^2`` over the batch.

    ``wrt`` indexes the flat leaf order (``flat_leaf_tensors``; bra
    slots — the trailing ``n`` leaves — are the sweep axis and cannot be
    differentiated here). Returns ``(amps, grads)``; cotangents follow
    the same ``df = Re(sum(g * dT))`` convention as
    :mod:`tnc_tpu.ops.autodiff`.
    """
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import _run_steps

    if not bitstrings:
        raise ValueError("amplitude_sweep_value_and_grad needs >= 1 bitstring")
    program, host_arrays, bra_slots = _sweep_program(
        circuit, bitstrings, pathfinder
    )
    bra_set = set(bra_slots)
    n_slots = len(host_arrays)
    arrays = [jnp.asarray(a, dtype=dtype) for a in host_arrays]

    from tnc_tpu.ops.autodiff import _validate_wrt

    if wrt is None:
        wrt = [s for s in range(n_slots) if s not in bra_set]
    wrt = _validate_wrt(wrt, n_slots)
    for s in wrt:
        if s in bra_set:
            raise ValueError(
                "bra slots carry the sweep axis; not differentiable"
            )

    if scalar_fn is None:

        def scalar_fn(amps):
            return jnp.sum(jnp.abs(amps) ** 2)

    def forward(diff_arrays):
        buffers = list(arrays)
        for slot, arr in zip(wrt, diff_arrays):
            buffers[slot] = arr

        def single(bra_values):
            per = list(buffers)
            for i, slot in enumerate(bra_slots):
                per[slot] = bra_values[i]
            return _run_steps(jnp, program, per).reshape(-1)[0]

        bras = jnp.stack([buffers[s] for s in bra_slots], axis=1)  # (B,n,2)
        amps = jax.vmap(single)(bras)
        return scalar_fn(amps), amps

    diff_in = tuple(arrays[slot] for slot in wrt)
    (_scalar, amps), grads = jax.value_and_grad(forward, has_aux=True)(
        diff_in
    )
    return (
        np.asarray(amps).reshape(len(bitstrings)),
        [np.asarray(g) for g in grads],
    )
