"""Tensor-network contraction.

Public equivalent of ``tnc/src/tensornetwork/contraction.rs:35-68``:
``contract_tensor_network(tn, path)`` fully contracts a (possibly nested)
network along a replace-left path and returns the resulting leaf tensor.

Unlike the reference's step-at-a-time TBLIS loop, the path is first
compiled to a static :class:`~tnc_tpu.ops.program.ContractionProgram` and
then executed by a pluggable backend — ``numpy`` (CPU oracle) or ``jax``
(whole-path jit on TPU). Leaf data (gates, files) is materialized lazily
here, at the host→device boundary, matching the reference's lazy
``TensorData::into_data`` (``tensordata.rs:37-56``).
"""

from __future__ import annotations

import logging

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.ops.backends import Backend, get_backend
from tnc_tpu.ops.program import build_program, flat_leaf_tensors
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData

logger = logging.getLogger(__name__)


def contract_tensor_network(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    backend: str | Backend | None = None,
) -> LeafTensor:
    """Fully contract ``tn`` along ``contract_path`` (replace-left format).

    Returns a :class:`LeafTensor` holding the fully-contracted data. Its
    legs carry the same ids as the reference's ``^``-fold
    (``contraction.rs:70-86``) but may be ordered differently — the
    program compiler picks the buffer order that tiles best on TPU, and
    ``result_legs`` records it; consumers address legs by id.

    >>> import numpy as np
    >>> from tnc_tpu.contractionpath.contraction_path import path
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> a = LeafTensor([0], [2]); a.data = TensorData.matrix(np.array([1.0, 2.0]))
    >>> b = LeafTensor([0], [2]); b.data = TensorData.matrix(np.array([3.0, 4.0]))
    >>> out = contract_tensor_network(CompositeTensor([a, b]), path((0, 1)))
    >>> complex(out.data.into_data())   # 1*3 + 2*4
    (11+0j)
    """
    backend_obj = get_backend(backend)
    program = build_program(tn, contract_path)
    # mirror of the reference's contraction debug records
    # (tensornetwork/contraction.rs:36,58)
    logger.debug(
        "contract: %d tensors, %d steps, backend=%s",
        len(program.steps) + 1 if program.steps else 1,
        len(program.steps),
        backend_obj.name,
    )
    leaves = flat_leaf_tensors(tn)
    arrays = [leaf.data.into_data() for leaf in leaves]
    result = backend_obj.execute(program, arrays)
    logger.debug(
        "contract done: result shape %s", tuple(program.result_shape)
    )
    return _canonical_result(program, result)


def _canonical_result(program, result) -> LeafTensor:
    """Permute a result buffer to the reference's ``^``-fold leg order
    (host-side; the device buffer keeps its TPU-friendly order)."""
    import numpy as np

    perm = program.canonical_perm()
    if perm is not None:
        result = np.transpose(np.asarray(result), perm)
    dim_of = dict(zip(program.result_legs, program.result_shape))
    return LeafTensor(
        list(program.canonical_legs),
        [dim_of[leg] for leg in program.canonical_legs],
        TensorData.matrix(result),
    )


def contract_tensor_network_sliced(
    tn: CompositeTensor,
    contract_path: ContractionPath,
    slicing,
    backend: str | Backend | None = None,
) -> LeafTensor:
    """Contract a network with the given legs sliced: the path executes
    once per slice-index combination and results are summed. Peak memory
    drops by the product of sliced dims (the capability the reference
    lists as future work; see ``tnc_tpu.contractionpath.slicing``).
    """
    from tnc_tpu.ops.sliced import build_sliced_program

    backend_obj = get_backend(backend)
    sp = build_sliced_program(tn, contract_path, slicing)
    leaves = flat_leaf_tensors(tn)
    arrays = [leaf.data.into_data() for leaf in leaves]
    result = backend_obj.execute_sliced(sp, arrays)
    return _canonical_result(sp.program, result)
