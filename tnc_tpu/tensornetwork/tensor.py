"""Tensor and tensor-network structure.

Host-side metadata mirror of the reference's tensor core
(``tnc/src/tensornetwork/tensor.rs:20-63``): a tensor network *is* a tensor.
``Tensor`` is either a ``LeafTensor`` (ordered legs + bond dims + lazy data)
or a ``CompositeTensor`` (a list of child tensors, arbitrarily nested). The
recursive structure directly encodes the parallel decomposition: top-level
children of a partitioned network are one partition per device, each child a
local tensor network.

Legs are *ordered* integer edge ids; the set-algebra operators preserve
order the same way the reference does (``tensor.rs:629-725``):

- ``a - b``  : legs in ``a`` not in ``b`` (order of ``a``)
- ``a | b``  : legs of ``a`` then legs of ``b`` not in ``a``
- ``a & b``  : legs of ``a`` that are in ``b``
- ``a ^ b``  : ``(a - b)`` then ``(b - a)`` — **the shape of a pairwise
  contraction result**, used everywhere.

Data never lives here; ``TensorData`` materializes lazily at contraction
time (``tensordata.rs:37-56``).
"""

from __future__ import annotations

import enum
from typing import Iterable, Iterator, Mapping, Sequence, Union

from tnc_tpu.tensornetwork.tensordata import TensorData
from tnc_tpu.utils.datastructures import UnionFind

EdgeIndex = int
TensorIndex = int

Tensor = Union["LeafTensor", "CompositeTensor"]
# any sequence of tensors (the ``TensorList`` trait, ``tensor.rs:134``)
TensorList = Sequence["Tensor"]


class TensorType(enum.Enum):
    """The type of a tensor (``tensor.rs:37-41``)."""

    COMPOSITE = "composite"
    LEAF = "leaf"


class LeafTensor:
    """A single tensor: ordered legs, bond dimensions, and (lazy) data.

    Mirrors ``LeafTensor`` in ``tensor.rs`` including ``new_from_map`` /
    ``new_from_const`` constructors (``tensor.rs:476-495``) and the
    ``size()`` product-of-dims metric computed in float to avoid overflow
    (``tensor.rs:571-573``).
    """

    __slots__ = ("legs", "bond_dims", "data")

    def __init__(
        self,
        legs: Sequence[EdgeIndex] = (),
        bond_dims: Sequence[int] = (),
        data: TensorData | None = None,
    ) -> None:
        if len(legs) != len(bond_dims):
            raise ValueError(
                f"legs ({len(legs)}) and bond_dims ({len(bond_dims)}) differ in length"
            )
        self.legs: list[EdgeIndex] = list(legs)
        self.bond_dims: list[int] = list(bond_dims)
        self.data: TensorData = data if data is not None else TensorData.none()

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_map(
        cls, legs: Sequence[EdgeIndex], bond_dims_map: Mapping[EdgeIndex, int]
    ) -> "LeafTensor":
        """Build from a ``{leg: dim}`` map (``tensor.rs:476`` new_from_map).

        >>> t = LeafTensor.from_map([0, 2], {0: 2, 2: 4})
        >>> t.shape
        (2, 4)
        >>> t.size()
        8.0
        """
        return cls(legs, [bond_dims_map[leg] for leg in legs])

    @classmethod
    def from_const(cls, legs: Sequence[EdgeIndex], bond_dim: int) -> "LeafTensor":
        """Build with all legs sharing one dim (``tensor.rs:492`` new_from_const)."""
        return cls(legs, [bond_dim] * len(legs))

    # -- basic queries -----------------------------------------------------

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.bond_dims)

    def dims(self) -> int:
        """Number of legs (tensor order)."""
        return len(self.legs)

    def size(self) -> float:
        """Number of elements, as float (large networks overflow ints)."""
        out = 1.0
        for d in self.bond_dims:
            out *= d
        return out

    def edges(self) -> Iterator[tuple[EdgeIndex, int]]:
        return zip(self.legs, self.bond_dims)

    def kind(self) -> TensorType:
        return TensorType.LEAF

    def is_leaf(self) -> bool:
        return True

    def is_composite(self) -> bool:
        return False

    def copy(self) -> "LeafTensor":
        return LeafTensor(self.legs, self.bond_dims, self.data)

    # -- leg set algebra (order-preserving, tensor.rs:629-777) -------------

    def difference(self, other: "LeafTensor") -> "LeafTensor":
        other_legs = set(other.legs)
        legs, dims = [], []
        for leg, dim in self.edges():
            if leg not in other_legs:
                legs.append(leg)
                dims.append(dim)
        return LeafTensor(legs, dims)

    def union(self, other: "LeafTensor") -> "LeafTensor":
        self_legs = set(self.legs)
        legs = list(self.legs)
        dims = list(self.bond_dims)
        for leg, dim in other.edges():
            if leg not in self_legs:
                legs.append(leg)
                dims.append(dim)
        return LeafTensor(legs, dims)

    def intersection(self, other: "LeafTensor") -> "LeafTensor":
        other_legs = set(other.legs)
        legs, dims = [], []
        for leg, dim in self.edges():
            if leg in other_legs:
                legs.append(leg)
                dims.append(dim)
        return LeafTensor(legs, dims)

    def symmetric_difference(self, other: "LeafTensor") -> "LeafTensor":
        """``(self - other) ++ (other - self)`` — the contraction-result legs.

        >>> a = LeafTensor.from_const([0, 1, 2], 2)
        >>> b = LeafTensor.from_const([1, 2, 3], 2)
        >>> (a ^ b).legs   # contraction result of a·b
        [0, 3]
        >>> (a & b).legs   # shared (contracted) legs
        [1, 2]
        """
        self_legs = set(self.legs)
        other_legs = set(other.legs)
        legs, dims = [], []
        for leg, dim in self.edges():
            if leg not in other_legs:
                legs.append(leg)
                dims.append(dim)
        for leg, dim in other.edges():
            if leg not in self_legs:
                legs.append(leg)
                dims.append(dim)
        return LeafTensor(legs, dims)

    __sub__ = difference
    __or__ = union
    __and__ = intersection
    __xor__ = symmetric_difference

    # -- equality / repr ---------------------------------------------------

    def allclose(
        self,
        other: "LeafTensor",
        rtol: float = 1e-8,
        atol: float = 1e-12,
    ) -> bool:
        """Approximate equality: same legs/bond dims AND elementwise-close
        materialized data — the ``AbsDiffEq``/``RelativeEq`` surface the
        reference implements for tensors
        (``tnc/src/tensornetwork/tensor.rs:417-435,779-820``). Tensors
        whose data is symbolic (:class:`~tnc_tpu.tensornetwork.tensordata.
        TensorData` gate/file refs) are materialized for the comparison;
        two data-less tensors compare by structure alone.
        """
        if not isinstance(other, LeafTensor):
            return False
        if self.legs != other.legs or self.bond_dims != other.bond_dims:
            return False
        import numpy as np

        from tnc_tpu.tensornetwork.tensordata import DataKind

        a_none = self.data.kind is DataKind.NONE
        b_none = other.data.kind is DataKind.NONE
        if a_none or b_none:
            return a_none and b_none  # metadata-only: structure decides
        a = np.asarray(self.data.into_data())
        b = np.asarray(other.data.into_data())
        return a.shape == b.shape and bool(
            np.allclose(a, b, rtol=rtol, atol=atol)
        )

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, LeafTensor):
            return NotImplemented
        return self.legs == other.legs and self.bond_dims == other.bond_dims

    def __hash__(self) -> int:
        return hash((tuple(self.legs), tuple(self.bond_dims)))

    def __repr__(self) -> str:
        return f"LeafTensor(legs={self.legs}, bond_dims={self.bond_dims})"


class CompositeTensor:
    """A tensor network: an ordered list of child tensors (leaf or composite).

    Mirrors ``CompositeTensor`` in ``tensor.rs``; supports arbitrary nesting.
    Top-level children of a partitioned network map one-to-one onto devices.
    """

    __slots__ = ("tensors",)

    def __init__(self, tensors: Iterable[Tensor] = ()) -> None:
        self.tensors: list[Tensor] = list(tensors)

    # -- collection interface ----------------------------------------------

    def __len__(self) -> int:
        return len(self.tensors)

    def __iter__(self) -> Iterator[Tensor]:
        return iter(self.tensors)

    def __getitem__(self, index: int) -> Tensor:
        return self.tensors[index]

    def push_tensor(self, tensor: Tensor) -> None:
        self.tensors.append(tensor)

    def push_tensors(self, tensors: Iterable[Tensor]) -> None:
        self.tensors.extend(tensors)

    def kind(self) -> TensorType:
        return TensorType.COMPOSITE

    def is_leaf(self) -> bool:
        return False

    def is_composite(self) -> bool:
        return True

    def copy(self) -> "CompositeTensor":
        """Deep copy of the nesting structure (leaf data shared)."""
        return CompositeTensor(t.copy() for t in self.tensors)

    def nested_tensor(self, index_path: Sequence[int]) -> Tensor:
        """Hierarchical indexing (``tensor.rs:303-309``)."""
        tensor: Tensor = self
        for idx in index_path:
            if not isinstance(tensor, CompositeTensor):
                raise TypeError("nested_tensor path descends through a leaf")
            tensor = tensor.tensors[idx]
        return tensor

    def total_num_tensors(self) -> int:
        """Count of all leaf tensors, recursively (``tensor.rs:312-321``)."""
        total = 0
        for t in self.tensors:
            total += t.total_num_tensors() if isinstance(t, CompositeTensor) else 1
        return total

    # -- network-level queries ---------------------------------------------

    def external_tensor(self) -> LeafTensor:
        """Open legs of the network, as a leaf: fold ``^`` over all children
        (``tensor.rs:392-402``). Legs shared by an *even* number of children
        cancel; the rest are external.
        """
        result = LeafTensor()
        for t in self.tensors:
            leaf = t.external_tensor() if isinstance(t, CompositeTensor) else t
            result = result ^ leaf
        return result

    def is_connected(self) -> bool:
        """Whether the network's leg-sharing graph is connected, via
        union-find (``tensor.rs:368-389``).
        """
        n = len(self.tensors)
        if n <= 1:
            return True
        uf = UnionFind(n)
        leg_owner: dict[EdgeIndex, int] = {}
        for i, t in enumerate(self.tensors):
            leaf = t.external_tensor() if isinstance(t, CompositeTensor) else t
            for leg in leaf.legs:
                if leg in leg_owner:
                    uf.union(leg_owner[leg], i)
                else:
                    leg_owner[leg] = i
        root = uf.find(0)
        return all(uf.find(i) == root for i in range(1, n))

    def bond_dims_map(self) -> dict[EdgeIndex, int]:
        """All ``{leg: dim}`` pairs appearing anywhere in the network."""
        out: dict[EdgeIndex, int] = {}
        stack: list[Tensor] = list(self.tensors)
        while stack:
            t = stack.pop()
            if isinstance(t, CompositeTensor):
                stack.extend(t.tensors)
            else:
                for leg, dim in t.edges():
                    out[leg] = dim
        return out

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, CompositeTensor):
            return NotImplemented
        return self.tensors == other.tensors

    def __repr__(self) -> str:
        return f"CompositeTensor({len(self.tensors)} tensors)"
