from tnc_tpu.tensornetwork.tensor import (  # noqa: F401
    CompositeTensor,
    LeafTensor,
    Tensor,
)
from tnc_tpu.tensornetwork.tensordata import TensorData  # noqa: F401
from tnc_tpu.tensornetwork.sweep import amplitude_sweep  # noqa: F401
