from tnc_tpu.tensornetwork.tensor import (  # noqa: F401
    CompositeTensor,
    LeafTensor,
    Tensor,
)
from tnc_tpu.tensornetwork.tensordata import TensorData  # noqa: F401
