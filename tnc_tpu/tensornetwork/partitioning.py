"""Tensor-network partitioning.

Public equivalent of ``tnc/src/tensornetwork/partitioning.rs``:

- :func:`find_partitioning` — split a network into ``k`` balanced blocks
  minimizing the (log-weighted) cut, via the native multilevel partitioner
  (the reference calls KaHyPar here, ``partitioning.rs:31-90``; 3%
  imbalance as in ``partitioning.rs:47``).
- :func:`communication_partitioning` — same, but vertices are weighted by
  intermediate-tensor cost supplied by the caller
  (``partitioning.rs:100-160``).
- :func:`partition_tensor_network` — regroup tensors into one nested
  composite per block (``partitioning.rs:164-174``).

In the distributed executor, top-level children map one-to-one onto mesh
devices.
"""

from __future__ import annotations

import enum
import logging
import random
from typing import Sequence

from tnc_tpu.partitioning.bisect import partition_kway
from tnc_tpu.partitioning.hypergraph import hypergraph_from_tensors
from tnc_tpu.tensornetwork.tensor import CompositeTensor

logger = logging.getLogger(__name__)


class PartitioningStrategy(enum.Enum):
    """Partitioner configuration presets (``partition_config.rs:12-36``).

    MIN_CUT maps to cut-minimizing bisection; COMMUNITY_FINDING biases
    toward connectivity (km1-style) — with recursive bisection both
    reduce to the same objective, kept as distinct presets for parity.
    """

    MIN_CUT = "min_cut"
    COMMUNITY_FINDING = "community_finding"


def find_partitioning(
    tn: CompositeTensor,
    k: int,
    strategy: PartitioningStrategy = PartitioningStrategy.MIN_CUT,
    balanced: bool = True,
    imbalance: float = 0.03,
    seed: int = 42,
) -> list[int]:
    """Block id per top-level tensor of ``tn``, in ``0..k``.
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor.from_const([i, i + 1], 2)
    ...                       for i in range(6)])
    >>> parts = find_partitioning(tn, 2)
    >>> len(parts), sorted(set(parts))
    (6, [0, 1])
    """
    if k <= 0:
        raise ValueError("k must be positive")
    if k == 1:
        return [0] * len(tn)
    hg = hypergraph_from_tensors(
        tn.tensors, unit_vertex_weights=strategy is PartitioningStrategy.MIN_CUT
    )
    eps = imbalance if balanced else 0.3
    logger.debug(
        "partition: %d tensors, %d hyperedges -> k=%d (%s, imbalance %.2f)",
        hg.num_vertices,
        len(hg.edge_pins),
        k,
        strategy.value,
        eps,
    )
    return partition_kway(hg, k, eps, random.Random(seed))


def communication_partitioning(
    tn: CompositeTensor,
    k: int,
    tensor_weights: Sequence[float],
    imbalance: float = 0.03,
    seed: int = 42,
) -> list[int]:
    """Partitioning for communication scheduling: vertex weights are the
    caller-supplied per-tensor costs (e.g. intermediate sizes)."""
    hg = hypergraph_from_tensors(tn.tensors)
    if len(tensor_weights) != hg.num_vertices:
        raise ValueError("tensor_weights length must match tensor count")
    hg.vertex_weights = [max(1.0, float(w)) for w in tensor_weights]
    return partition_kway(hg, k, imbalance, random.Random(seed))


def partition_tensor_network(
    tn: CompositeTensor, partitioning: Sequence[int]
) -> CompositeTensor:
    """Regroup top-level tensors into one nested composite per block.

    Blocks are ordered by block id; empty blocks are dropped. Tensor order
    within a block follows the original order, as in the reference.
    """
    if len(partitioning) != len(tn):
        raise ValueError("partitioning length must match tensor count")
    blocks: dict[int, CompositeTensor] = {}
    for tensor, block in zip(tn.tensors, partitioning):
        blocks.setdefault(block, CompositeTensor()).push_tensor(tensor)
    return CompositeTensor([blocks[b] for b in sorted(blocks)])
