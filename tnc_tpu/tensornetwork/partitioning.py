"""Tensor-network partitioning.

Public equivalent of ``tnc/src/tensornetwork/partitioning.rs``:

- :func:`find_partitioning` — split a network into ``k`` balanced blocks
  minimizing the (log-weighted) cut, via the native multilevel partitioner
  (the reference calls KaHyPar here, ``partitioning.rs:31-90``; 3%
  imbalance as in ``partitioning.rs:47``).
- :func:`communication_partitioning` — same, but vertices are weighted by
  intermediate-tensor cost supplied by the caller
  (``partitioning.rs:100-160``).
- :func:`partition_tensor_network` — regroup tensors into one nested
  composite per block (``partitioning.rs:164-174``).

In the distributed executor, top-level children map one-to-one onto mesh
devices.
"""

from __future__ import annotations

import enum
import logging
import random
from dataclasses import dataclass
from typing import Sequence

from tnc_tpu import obs
from tnc_tpu.partitioning.bisect import partition_kway
from tnc_tpu.partitioning.hypergraph import hypergraph_from_tensors
from tnc_tpu.tensornetwork.tensor import CompositeTensor

logger = logging.getLogger(__name__)


class PartitioningStrategy(enum.Enum):
    """Partitioner configuration presets (``partition_config.rs:12-36``).

    MIN_CUT minimizes the cut (hyperedges spanning >1 block);
    COMMUNITY_FINDING minimizes connectivity (km1:
    ``sum_e w_e * (lambda_e - 1)``) via a direct k-way refinement pass
    after recursive bisection, penalizing bonds *scattered over many*
    blocks — each extra block touched is one more fan-in transfer in
    the distributed runtime. The objectives coincide at k=2 and
    genuinely diverge for k>2, mirroring the two KaHyPar configs the
    reference embeds.
    """

    MIN_CUT = "min_cut"
    COMMUNITY_FINDING = "community_finding"


@dataclass(frozen=True)
class PartitionConfig:
    """User-supplied partitioner configuration — the escape hatch the
    reference exposes as ``PartitionConfig::Custom(path)`` (a KaHyPar
    config file, ``partition_config.rs:12-36``); here a plain object
    since the partitioner is native to the package.

    ``objective``: ``"cut"`` or ``"km1"`` (see
    :class:`PartitioningStrategy`). ``unit_vertex_weights``: balance
    tensor *counts* (True) or log-sizes (False).
    """

    objective: str = "cut"
    imbalance: float = 0.03
    seed: int = 42
    refine_passes: int = 8
    unit_vertex_weights: bool = True

    @classmethod
    def for_strategy(
        cls, strategy: PartitioningStrategy, imbalance: float, seed: int
    ) -> "PartitionConfig":
        if strategy is PartitioningStrategy.MIN_CUT:
            return cls(
                objective="cut", imbalance=imbalance, seed=seed,
                unit_vertex_weights=True,
            )
        return cls(
            objective="km1", imbalance=imbalance, seed=seed,
            unit_vertex_weights=False,
        )


@obs.traced("plan.find_partitioning")
def find_partitioning(
    tn: CompositeTensor,
    k: int,
    strategy: PartitioningStrategy = PartitioningStrategy.MIN_CUT,
    balanced: bool = True,
    imbalance: float = 0.03,
    seed: int = 42,
    config: PartitionConfig | None = None,
) -> list[int]:
    """Block id per top-level tensor of ``tn``, in ``0..k``.

    ``config`` overrides the preset entirely (the reference's
    ``Custom(path)`` escape hatch).
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor.from_const([i, i + 1], 2)
    ...                       for i in range(6)])
    >>> parts = find_partitioning(tn, 2)
    >>> len(parts), sorted(set(parts))
    (6, [0, 1])
    """
    if k <= 0:
        raise ValueError("k must be positive")
    if k == 1:
        return [0] * len(tn)
    if config is None:
        config = PartitionConfig.for_strategy(strategy, imbalance, seed)
    hg = hypergraph_from_tensors(
        tn.tensors, unit_vertex_weights=config.unit_vertex_weights
    )
    eps = config.imbalance if balanced else 0.3
    logger.debug(
        "partition: %d tensors, %d hyperedges -> k=%d (%s, imbalance %.2f)",
        hg.num_vertices,
        len(hg.edge_pins),
        k,
        config.objective,
        eps,
    )
    return partition_kway(
        hg,
        k,
        eps,
        random.Random(config.seed),
        objective=config.objective,
        refine_passes=config.refine_passes,
    )


def communication_partitioning(
    tn: CompositeTensor,
    k: int,
    tensor_weights: Sequence[float],
    imbalance: float = 0.03,
    seed: int = 42,
) -> list[int]:
    """Partitioning for communication scheduling: vertex weights are the
    caller-supplied per-tensor costs (e.g. intermediate sizes)."""
    hg = hypergraph_from_tensors(tn.tensors)
    if len(tensor_weights) != hg.num_vertices:
        raise ValueError("tensor_weights length must match tensor count")
    hg.vertex_weights = [max(1.0, float(w)) for w in tensor_weights]
    return partition_kway(hg, k, imbalance, random.Random(seed))


def partition_tensor_network(
    tn: CompositeTensor, partitioning: Sequence[int]
) -> CompositeTensor:
    """Regroup top-level tensors into one nested composite per block.

    Blocks are ordered by block id; empty blocks are dropped. Tensor order
    within a block follows the original order, as in the reference.
    """
    if len(partitioning) != len(tn):
        raise ValueError("partitioning length must match tensor count")
    blocks: dict[int, CompositeTensor] = {}
    for tensor, block in zip(tn.tensors, partitioning):
        blocks.setdefault(block, CompositeTensor()).push_tensor(tensor)
    return CompositeTensor([blocks[b] for b in sorted(blocks)])
