"""Exact network preprocessing: absorb rank<=2 tensors numerically.

Quantum-circuit networks are dominated by rank-1 kets/bras and rank-2
single-qubit gates. Contracting them into their neighbours is exact,
costs microseconds on host, and shrinks a Sycamore-53 network from ~1200
tensors to ~250 rank>=3 cores. Doing this on the **host** before planning
and device execution:

- makes the partition-based pathfinder dramatically better (the cores are
  what matters),
- shrinks the XLA program from ~1200 unrolled steps to the few hundred
  that carry all the FLOPs (compile time and memory scale with program
  size),
- keeps the MXU fed with real matmuls instead of 2x2 trivia.

This is a TPU-first division of labour the reference doesn't need (TBLIS
calls are cheap to issue one at a time; ``contraction.rs:52-57``).
"""

from __future__ import annotations

from collections import deque

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


def _contract_pair_np(a: LeafTensor, b: LeafTensor) -> LeafTensor:
    """Pairwise contraction on host, legs ordered as ``a ^ b``
    (``tensordot`` free-leg order matches the reference's ``^``)."""
    b_set = set(b.legs)
    a_set = set(a.legs)
    shared = [leg for leg in a.legs if leg in b_set]
    a_pos = [a.legs.index(leg) for leg in shared]
    b_pos = [b.legs.index(leg) for leg in shared]
    da = np.asarray(a.data.into_data(), dtype=np.complex128)
    db = np.asarray(b.data.into_data(), dtype=np.complex128)
    out = np.tensordot(da, db, axes=(a_pos, b_pos))
    out_legs = [leg for leg in a.legs if leg not in b_set] + [
        leg for leg in b.legs if leg not in a_set
    ]
    dim_of = dict(a.edges())
    dim_of.update(b.edges())
    result = LeafTensor(out_legs, [dim_of[leg] for leg in out_legs])
    result.data = TensorData.matrix(out)
    return result


def simplify_network(tn: CompositeTensor, max_rank: int = 2) -> CompositeTensor:
    """Contract every tensor of rank <= ``max_rank`` into a neighbour,
    repeatedly, materializing data on host. Returns the reduced network
    (flat; surviving tensors keep their relative order).

    Disconnected low-rank tensors (no shared legs) are left in place.
    The result is numerically identical to contracting the original
    network: only exact pairwise contractions are applied.

    >>> import numpy as np
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> ket0 = LeafTensor([0], [2]); ket0.data = TensorData.matrix(np.array([1.0, 0]))
    >>> ket1 = LeafTensor([1], [2]); ket1.data = TensorData.matrix(np.array([0, 1.0]))
    >>> core = LeafTensor([0, 1, 2], [2, 2, 2])
    >>> core.data = TensorData.matrix(np.arange(8.0).reshape(2, 2, 2))
    >>> reduced = simplify_network(CompositeTensor([ket0, ket1, core]))
    >>> len(reduced)   # one ket absorbed; networks stop shrinking at 2
    2
    """
    tensors: dict[int, LeafTensor] = {i: t for i, t in enumerate(tn.tensors)}
    if any(isinstance(t, CompositeTensor) for t in tn.tensors):
        raise ValueError("simplify_network expects a flat network")

    leg_owners: dict[int, set[int]] = {}
    for i, t in tensors.items():
        for leg in t.legs:
            leg_owners.setdefault(leg, set()).add(i)

    next_id = len(tn.tensors)
    order: list[int] = list(tensors)  # insertion order for stable output

    queue = deque(i for i, t in tensors.items() if t.dims() <= max_rank)
    while queue:
        i = queue.popleft()
        if i not in tensors or tensors[i].dims() > max_rank:
            continue
        if len(tensors) <= 2:
            break
        neighbour = -1
        neighbour_rank = 1 << 30
        for leg in tensors[i].legs:
            for j in leg_owners.get(leg, ()):
                if j != i and j in tensors and tensors[j].dims() < neighbour_rank:
                    neighbour = j
                    neighbour_rank = tensors[j].dims()
        if neighbour < 0:
            continue  # disconnected; leave it

        merged = _contract_pair_np(tensors[i], tensors[neighbour])
        for leg in set(tensors[i].legs) | set(tensors[neighbour].legs):
            owners = leg_owners.get(leg)
            if owners is not None:
                owners.discard(i)
                owners.discard(neighbour)
        del tensors[i], tensors[neighbour]

        new_id = next_id
        next_id += 1
        tensors[new_id] = merged
        order.append(new_id)
        for leg in merged.legs:
            leg_owners.setdefault(leg, set()).add(new_id)
        if merged.dims() <= max_rank:
            queue.append(new_id)

    surviving = [tensors[i] for i in order if i in tensors]
    return CompositeTensor(surviving)
