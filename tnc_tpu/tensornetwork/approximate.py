"""Approximate contraction: boundary-MPS with SVD truncation.

The reference lists approximate contraction as future work
(``book/src/future_work.md``); this module implements the standard
boundary-MPS scheme for 2-D grid networks (PEPS sandwiches, and the
qubit×depth grids :mod:`tnc_tpu.approx.program` flattens circuits
into): the top row is an MPS, every interior row an MPO; after each
MPS·MPO application the boundary MPS is compressed to bond dimension
``chi`` by a QR canonicalization sweep followed by truncated SVDs.
Memory and time are then polynomial in ``chi`` instead of exponential
in the grid width — the classic accuracy-for-cost dial exact
contraction lacks.

Beyond the value, every sweep reports its **accumulated discarded SVD
weight** (:func:`boundary_contract_with_weight`) — the sum over all
truncations of the relative discarded singular-value mass. Zero weight
means nothing was truncated and the sweep is exact (up to roundoff);
the :mod:`tnc_tpu.approx.ladder` chi-ladder turns the weight plus
inter-rung deltas into a per-answer error estimate.

Scope notes:

- Sites may be connected by *several* parallel bonds (a PEPS sandwich
  has one bond per layer between neighbours); bonds per direction are
  fused into one dense axis, neighbours aligned by sorted leg id.
- The linear algebra runs through numpy at complex128 (QR/SVD of
  χ-sized matrices — planner-scale host work, like pathfinding; the
  contraction dial is what matters on TPU: pick ``chi`` so the exact
  *sliced* plan of the compressed network fits, or use the boundary
  value directly).
- ``backend="jax"`` streams the sweep row by row through a per-row
  jitted apply+compress step (cached per (shapes, chi)), so only ONE
  interior row's dense site tensors are materialized at a time — the
  documented one-row-alive memory bound holds on both backends.
- ``collapse_peps_sandwich`` flattens the ``builders.peps`` sandwich
  (layer-major ordering, ``peps.rs:446-460`` equivalent) into the
  single-layer grid this module consumes.
"""

from __future__ import annotations

import functools as _functools
from typing import Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData

#: accumulated relative discarded weight below this is roundoff, not
#: truncation — the sweep computed the closed network exactly (the
#: chi-ladder reports err ≈ 0 at such rungs)
EXACT_WEIGHT = 1e-20

#: complex128 element width (the bytes side of the sweep's roofline)
_ELEM_BYTES = 16


def _site_array(t: LeafTensor) -> np.ndarray:
    return np.asarray(t.data.into_data(), dtype=np.complex128).reshape(
        t.shape
    )


def _grouped(t: LeafTensor, groups: Sequence[Sequence[int]]) -> np.ndarray:
    """Dense site tensor with axes permuted/fused to the leg groups
    (one fused axis per group, legs within a group in the given order;
    missing groups become dim-1 axes)."""
    arr = _site_array(t)
    pos = {leg: i for i, leg in enumerate(t.legs)}
    perm: list[int] = []
    shape: list[int] = []
    for group in groups:
        size = 1
        for leg in group:
            perm.append(pos[leg])
            size *= t.bond_dims[pos[leg]]
        shape.append(size)
    if len(perm) != len(t.legs):
        raise ValueError(
            f"site tensor has legs {sorted(t.legs)} outside its grid "
            f"neighbourhood {sorted(l for g in groups for l in g)}"
        )
    return np.transpose(arr, perm).reshape(shape)


def _grid_groups(grid) -> list[list[tuple[list, list, list, list]]]:
    """Per-site ``(left, right, up, down)`` leg groups of a rectangular
    grid (shared validation for the contractor and the geometry/cost
    helpers)."""
    rows = len(grid)
    if rows < 2 or any(len(r) != len(grid[0]) for r in grid):
        raise ValueError("grid must be rectangular with >= 2 rows")
    cols = len(grid[0])
    if cols < 1:
        raise ValueError("grid rows must be non-empty")
    legs_of = [[set(t.legs) for t in row] for row in grid]

    def shared(r1, c1, r2, c2) -> list[int]:
        if 0 <= r2 < rows and 0 <= c2 < cols:
            return sorted(legs_of[r1][c1] & legs_of[r2][c2])
        return []

    return [
        [
            (
                shared(r, c, r, c - 1),   # left
                shared(r, c, r, c + 1),   # right
                shared(r, c, r - 1, c),   # up
                shared(r, c, r + 1, c),   # down
            )
            for c in range(cols)
        ]
        for r in range(rows)
    ]


def grid_site_dims(grid) -> list[list[tuple[int, int, int, int]]]:
    """Per-site fused ``(left, right, up, down)`` bond dims — the
    geometry the closed-form sweep cost model
    (:mod:`tnc_tpu.approx.cost`) walks without materializing any site
    data.

    >>> import numpy as np
    >>> from tnc_tpu.builders.peps import peps
    >>> rng = np.random.default_rng(0)
    >>> tn = attach_random_data(peps(3, 3, 2, 2, 0), rng)
    >>> grid = collapse_peps_sandwich(tn, 3, 3, 0)
    >>> grid_site_dims(grid)[1][1]  # interior site of a vd=2 sandwich
    (4, 4, 4, 4)
    """
    groups = _grid_groups(grid)
    out: list[list[tuple[int, int, int, int]]] = []
    for row, grow in zip(grid, groups):
        dims_row = []
        for t, site_groups in zip(row, grow):
            dim_of = dict(zip(t.legs, t.bond_dims))
            dims_row.append(
                tuple(
                    int(np.prod([dim_of[l] for l in g], initial=1))
                    for g in site_groups
                )
            )
        out.append(dims_row)
    return out


def _truncated_svd(m, chi: int, cutoff: float, xp=np):
    """Truncated SVD plus the **relative discarded weight** (discarded
    singular mass over total; 0.0 when nothing real was cut)."""
    u, s, vh = xp.linalg.svd(m, full_matrices=False)
    if xp is np:
        keep = int(np.sum(s > cutoff * (s[0] if s.size else 1.0)))
        keep = max(1, min(keep, chi))
        total = float(np.sum(s * s))
        disc = float(np.sum(s[keep:] * s[keep:]))
        rel = disc / total if total > 0.0 else 0.0
    else:
        # jitted path: the kept rank must be static, so the cut is by
        # chi alone (cutoff-based rank is value-dependent)
        keep = max(1, min(int(s.shape[0]), chi))
        total = xp.sum(s * s)
        disc = xp.sum(s[keep:] * s[keep:])
        rel = xp.where(total > 0.0, disc / total, 0.0)
    return u[:, :keep], s[:keep], vh[:keep], rel


def _compress_mps(mps, chi: int, cutoff: float, xp=np):
    """Canonicalize left-to-right (QR), then truncate right-to-left
    (SVD). Tensors are (Dl, d, Dr). Returns ``(mps, weight)`` where
    ``weight`` is the summed relative discarded SVD weight."""
    mps = list(mps)
    n = len(mps)
    weight = 0.0
    # left-to-right QR: left-canonical form
    for i in range(n - 1):
        dl, d, dr = mps[i].shape
        q, r = xp.linalg.qr(mps[i].reshape(dl * d, dr))
        mps[i] = q.reshape(dl, d, q.shape[1])
        mps[i + 1] = xp.tensordot(r, mps[i + 1], axes=(1, 0))
    # right-to-left truncated SVD
    for i in range(n - 1, 0, -1):
        dl, d, dr = mps[i].shape
        u, s, vh, rel = _truncated_svd(
            mps[i].reshape(dl, d * dr), chi, cutoff, xp
        )
        weight = weight + rel
        mps[i] = vh.reshape(vh.shape[0], d, dr)
        carry = u * s  # (dl, keep)
        mps[i - 1] = xp.tensordot(mps[i - 1], carry, axes=(2, 0))
    return mps, weight


def _apply_mpo(mps, mpo, xp=np):
    """MPS (Dl, d_up, Dr) x MPO (Wl, Wr, d_up, d_down) →
    fat MPS (Dl·Wl, d_down, Dr·Wr)."""
    out = []
    for a, w in zip(mps, mpo):
        dl, dup, dr = a.shape
        wl, wr, wup, wdown = w.shape
        if dup != wup:
            raise ValueError(f"vertical bond mismatch: {dup} vs {wup}")
        t = xp.tensordot(a, w, axes=(1, 2))  # (dl, dr, wl, wr, wdown)
        t = xp.transpose(t, (0, 2, 4, 1, 3))  # (dl, wl, wdown, dr, wr)
        out.append(t.reshape(dl * wl, wdown, dr * wr))
    return out


def _apply_compress(xp, mps, mpo, chi: int, cutoff: float):
    mps = _apply_mpo(mps, mpo, xp)
    return _compress_mps(mps, chi, cutoff, xp)


def _close(xp, mps, bottom):
    env = xp.ones((1, 1), dtype=mps[0].dtype)
    for a, site in zip(mps, bottom):
        # env (Dl, Bl) · a (Dl, d, Dr) · site (Bl, d, Br) -> (Dr, Br)
        tmp = xp.tensordot(env, a, axes=(0, 0))  # (Bl, d, Dr)
        env = xp.tensordot(tmp, site, axes=((0, 1), (0, 1)))
    return env


def row_cost(
    mps_shapes: Sequence[tuple], mpo_shapes: Sequence[tuple], chi: int
) -> tuple[float, float, int, list[tuple]]:
    """Leading-order cost of ONE apply+compress boundary step:
    ``(flops, bytes, ops, out_shapes)``.

    Flops are naive complex multiply-add counts (the same ``k·m·n``
    convention as :func:`tnc_tpu.ops.program.step_flops`, so
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` prices them in
    the domain it was fitted in); QR is counted as ``2·m·n·min`` and
    SVD as ``4·m·n·min``. ``bytes`` is the complex128 traffic of every
    operand read and result written; ``ops`` the dispatched linalg
    calls (the cost model's per-dispatch overhead multiplier);
    ``out_shapes`` the compressed boundary shapes, so a caller can walk
    a whole sweep row by row without materializing data
    (:func:`tnc_tpu.approx.cost.sweep_cost`)."""
    flops = 0.0
    elems = 0.0
    ops = 0
    shapes: list[tuple] = []
    for (dl, d, dr), (wl, wr, wup, wdown) in zip(mps_shapes, mpo_shapes):
        if d != wup:
            raise ValueError(f"vertical bond mismatch: {d} vs {wup}")
        flops += float(dl) * dr * d * wl * wr * wdown
        elems += dl * d * dr + wl * wr * wup * wdown
        elems += dl * wl * wdown * dr * wr
        ops += 1
        shapes.append((dl * wl, wdown, dr * wr))
    n = len(shapes)
    # left-to-right QR canonicalization
    for i in range(n - 1):
        dl, d, dr = shapes[i]
        m, k = dl * d, dr
        r = min(m, k)
        flops += 2.0 * m * k * r
        elems += m * k + m * r + r * k
        ops += 1
        shapes[i] = (dl, d, r)
        dl2, d2, dr2 = shapes[i + 1]
        flops += float(r) * k * d2 * dr2
        elems += r * k + k * d2 * dr2 + r * d2 * dr2
        ops += 1
        shapes[i + 1] = (r, d2, dr2)
    # right-to-left truncated SVD
    for i in range(n - 1, 0, -1):
        dl, d, dr = shapes[i]
        m, k = dl, d * dr
        r = min(m, k, chi)
        flops += 4.0 * m * k * min(m, k)
        elems += m * k + m * r + r * k
        ops += 1
        shapes[i] = (r, d, dr)
        dl0, d0, dr0 = shapes[i - 1]
        flops += float(dl0) * d0 * dr0 * r
        elems += dl0 * d0 * dr0 + dr0 * r + dl0 * d0 * r
        ops += 1
        shapes[i - 1] = (dl0, d0, r)
    return flops, elems * _ELEM_BYTES, ops, shapes


def close_cost(
    mps_shapes: Sequence[tuple], bottom_shapes: Sequence[tuple]
) -> tuple[float, float, int]:
    """Leading-order cost ``(flops, bytes, ops)`` of contracting the
    final boundary MPS against the bottom row."""
    flops = 0.0
    elems = 0.0
    ops = 0
    eb = 1
    for (dl, d, dr), (bl, bd, br) in zip(mps_shapes, bottom_shapes):
        # env (dl, eb) · a (dl, d, dr): k=dl, out (eb, d, dr)
        flops += float(eb) * dl * d * dr
        # tmp (eb, d, dr) · site (eb==bl, d, br): k=eb·d, out (dr, br)
        flops += float(eb) * d * dr * br
        elems += dl * eb + dl * d * dr + bl * bd * br + dr * br
        ops += 2
        eb = br
    return flops, elems * _ELEM_BYTES, ops


def _sweep_numpy(top, mid_rows, bottom, chi: int, cutoff: float):
    """Host sweep: one interior row's grouped site tensors alive at a
    time, one ``approx.row`` span per row carrying the row's
    closed-form flop/byte counts."""
    mps = list(top)
    weight = 0.0
    for r, mpo in enumerate(mid_rows, start=1):
        flops, nbytes, _ops, _shapes = row_cost(
            [a.shape for a in mps], [w.shape for w in mpo], chi
        )
        with obs.span("approx.row", row=r, chi=chi) as sp:
            mps, w = _apply_compress(np, mps, mpo, chi, cutoff)
            sp.add(flops=flops, bytes=nbytes)
        weight += float(w)
    env = _close(np, mps, bottom)
    return env, weight


@_functools.lru_cache(maxsize=256)
def _jax_row_fn(chi: int, mps_shapes: tuple, mpo_shapes: tuple):
    """One jitted apply+compress step per (shapes, chi) — the
    streaming sweep's unit of compilation. Distinct rows of one grid
    that share shapes (the steady state of a deep circuit grid) share
    one executable; repeat calls over same-geometry grids recompile
    nothing."""
    import jax

    def run(mps, mpo):
        import jax.numpy as jnp

        return _apply_compress(jnp, list(mps), list(mpo), chi, 0.0)

    return jax.jit(run)


@_functools.lru_cache(maxsize=64)
def _jax_close_fn(mps_shapes: tuple, bottom_shapes: tuple):
    import jax

    def run(mps, bottom):
        import jax.numpy as jnp

        return _close(jnp, list(mps), list(bottom))

    return jax.jit(run)


def _sweep_jax(top_fn, mid_iter, bottom_fn, chi: int):
    """Streaming device sweep: rows are grouped, transferred and
    consumed ONE AT A TIME (the same one-row-alive bound as the numpy
    path — materializing every row up front would defeat it on exactly
    the tall grids that need the boundary scheme), each through the
    per-(shapes, chi) jitted apply+compress step."""
    import jax

    # Complex QR/SVD only exists on CPU-like backends (the TPU path
    # of this stack is split-complex and has no complex dtypes), so
    # the sweep is pinned to the CPU platform explicitly — on an
    # accelerator-default environment the default device would be
    # the TPU and the program could not lower. (Platform discovery
    # initializes all registered JAX plugins; on a host whose
    # accelerator plugin wedges at init — the tunnel pathology in
    # docs/running_on_tpu.md — pin
    # ``jax.config.update("jax_platforms", "cpu")`` process-wide
    # first, as everywhere else in this stack.)
    cpu = jax.local_devices(backend="cpu")[0]
    dtype = (
        "complex128" if jax.config.read("jax_enable_x64") else "complex64"
    )

    def put_row(row):
        return [
            jax.device_put(np.asarray(a, dtype=dtype), cpu) for a in row
        ]

    with jax.default_device(cpu):
        mps = put_row(top_fn())
        weights = []
        for r, row in enumerate(mid_iter, start=1):
            mpo = put_row(row)
            mps_shapes = tuple(tuple(a.shape) for a in mps)
            mpo_shapes = tuple(tuple(w.shape) for w in mpo)
            flops, nbytes, _ops, _shapes = row_cost(
                mps_shapes, mpo_shapes, chi
            )
            with obs.span("approx.row", row=r, chi=chi) as sp:
                mps, w = _jax_row_fn(chi, mps_shapes, mpo_shapes)(mps, mpo)
                sp.add(flops=flops, bytes=nbytes)
            weights.append(w)
        bottom = put_row(bottom_fn())
        env = _jax_close_fn(
            tuple(tuple(a.shape) for a in mps),
            tuple(tuple(b.shape) for b in bottom),
        )(mps, bottom)
        weight = float(sum(float(np.asarray(w)) for w in weights))
    return np.asarray(env), weight


def boundary_contract_with_weight(
    grid: Sequence[Sequence[LeafTensor]],
    chi: int,
    cutoff: float = 0.0,
    backend: str = "numpy",
) -> tuple[complex, float]:
    """Contract a closed 2-D grid network approximately, returning
    ``(value, weight)`` where ``weight`` is the sweep's accumulated
    relative discarded SVD mass — ``0.0`` (or roundoff below
    :data:`EXACT_WEIGHT`) means no truncation happened and the value is
    exact up to floating point. The whole sweep runs under an
    ``approx.sweep`` obs span with per-row ``approx.row`` children
    carrying closed-form flop/byte counters."""
    rows = len(grid)
    groups = _grid_groups(grid)
    cols = len(grid[0])
    if chi < 1:
        raise ValueError("chi must be >= 1")
    if backend not in ("numpy", "jax"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend == "jax" and cutoff:
        raise ValueError(
            "cutoff-based rank is value-dependent; the jitted jax sweep "
            "supports chi truncation only"
        )

    def top_row():
        out = []
        for c in range(cols):
            left, right, up, down = groups[0][c]
            if up:
                raise ValueError("top row must have no upward bonds")
            out.append(_grouped(grid[0][c], (left, down, right)))
        return out

    def mid_rows():
        # lazy per row: only one interior row's dense grouped copies are
        # alive at a time (both backends — the jax path streams rows
        # through the per-row jitted step)
        for r in range(1, rows - 1):
            yield [
                _grouped(grid[r][c], groups[r][c]) for c in range(cols)
            ]

    def bottom_row():
        out = []
        for c in range(cols):
            left, right, up, down = groups[rows - 1][c]
            if down:
                raise ValueError("bottom row must have no downward bonds")
            out.append(_grouped(grid[rows - 1][c], (left, up, right)))
        return out

    with obs.span(
        "approx.sweep", rows=rows, cols=cols, chi=chi, backend=backend
    ):
        if backend == "jax":
            env, weight = _sweep_jax(top_row, mid_rows(), bottom_row, chi)
        else:
            env, weight = _sweep_numpy(
                top_row(), mid_rows(), bottom_row(), chi, cutoff
            )
    if env.shape != (1, 1):
        raise ValueError("grid did not close to a scalar")
    return complex(env[0, 0]), float(weight)


def boundary_mps_contract(
    grid: Sequence[Sequence[LeafTensor]],
    chi: int,
    cutoff: float = 0.0,
    backend: str = "numpy",
) -> complex:
    """Contract a closed 2-D grid network approximately.

    ``grid[r][c]`` are data-carrying leaf tensors whose legs connect
    only to the four lattice neighbours (parallel bonds allowed, fused
    per direction). ``chi`` caps the boundary-MPS bond dimension; with
    ``chi`` at least the exact boundary rank the result is exact.

    ``backend="jax"`` runs each boundary step as a jitted XLA program,
    explicitly pinned to the CPU platform (complex QR/SVD has no TPU
    lowering in this stack — the TPU execution path is split-complex):
    every intermediate shape is static given the grid, so compiled row
    steps are cached per (shapes, chi) and reused across rows AND
    calls, while rows stream through one at a time. The static-rank
    constraint means the value-dependent ``cutoff`` is numpy-only.

    >>> import numpy as np
    >>> from tnc_tpu.builders.peps import peps
    >>> rng = np.random.default_rng(7)
    >>> tn = attach_random_data(peps(3, 3, 2, 2, 1), rng)
    >>> from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    >>> from tnc_tpu.tensornetwork.contraction import contract_tensor_network
    >>> path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    >>> want = complex(contract_tensor_network(tn, path,
    ...     backend="numpy").data.into_data().reshape(-1)[0])
    >>> grid = collapse_peps_sandwich(tn, 3, 3, 1)
    >>> got = boundary_mps_contract(grid, chi=4096)  # chi >= exact rank
    >>> abs(got - want) <= 1e-8 * max(1.0, abs(want))
    True
    """
    value, _weight = boundary_contract_with_weight(
        grid, chi, cutoff=cutoff, backend=backend
    )
    return value


def collapse_peps_sandwich(
    tn: CompositeTensor, length: int, depth: int, layers: int
) -> list[list[LeafTensor]]:
    """Flatten a ``builders.peps`` sandwich (data attached) into the
    single-layer ``depth × length`` grid ``boundary_mps_contract``
    consumes: each site's ``layers + 2`` stacked tensors are contracted
    over their vertical physical bonds (greedy local path), leaving the
    per-layer horizontal bonds as parallel grid bonds. A failure inside
    one site's local contraction (wrong attached data shape, broken
    bonds) is re-raised naming the offending site ``(row, col)``."""
    from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    from tnc_tpu.tensornetwork.contraction import contract_tensor_network

    n_layers = layers + 2
    leaves = list(tn.tensors)
    if len(leaves) != n_layers * depth * length:
        raise ValueError(
            f"expected {n_layers * depth * length} tensors "
            f"(layer-major peps ordering), got {len(leaves)}"
        )

    def site_index(k, r, c):
        return k * depth * length + r * length + c

    grid: list[list[LeafTensor]] = []
    with obs.span(
        "approx.collapse", length=length, depth=depth, layers=layers
    ):
        for r in range(depth):
            row = []
            for c in range(length):
                stack = CompositeTensor(
                    [
                        leaves[site_index(k, r, c)].copy()
                        for k in range(n_layers)
                    ]
                )
                try:
                    result = Greedy(OptMethod.GREEDY).find_path(stack)
                    merged = contract_tensor_network(
                        stack, result.replace_path(), backend="numpy"
                    )
                except Exception as exc:
                    raise ValueError(
                        f"collapse_peps_sandwich: site (row {r}, col {c}) "
                        f"failed to contract its {n_layers}-layer stack "
                        f"({type(exc).__name__}: {exc})"
                    ) from exc
                row.append(merged)
            grid.append(row)
    return grid


def attach_random_data(
    tn: CompositeTensor, rng: np.random.Generator, scale: float | None = None
) -> CompositeTensor:
    """Fill every metadata-only leaf with seeded complex Gaussian data
    (builder networks like ``peps`` are metadata-only); leaves that
    already carry data (gates, matrices, file refs) are left untouched
    after validating that their payload matches the leaf's declared
    shape — a mismatch is reported naming the offending leaf and both
    shapes, not as a downstream reshape error. ``scale`` defaults to
    per-tensor ``1/sqrt(size)`` so contractions stay O(1)."""
    from tnc_tpu.tensornetwork.tensordata import DataKind

    with obs.span("approx.attach_data", leaves=len(tn.tensors)):
        for i, leaf in enumerate(tn.tensors):
            if isinstance(leaf, CompositeTensor):
                attach_random_data(leaf, rng, scale)
                continue
            if leaf.data.kind is not DataKind.NONE:
                have = int(np.asarray(leaf.data.into_data()).size)
                want = int(np.prod(leaf.shape, initial=1))
                if have != want:
                    raise ValueError(
                        f"attach_random_data: leaf {i} (legs "
                        f"{list(leaf.legs)}) carries data of {have} "
                        f"elements but its declared shape {leaf.shape} "
                        f"needs {want}"
                    )
                continue
            shape = leaf.shape
            s = scale if scale is not None else 1.0 / np.sqrt(
                max(1.0, float(np.prod(shape)))
            )
            data = (
                rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
            ) * s
            leaf.data = TensorData.matrix(data.astype(np.complex128))
    return tn
