"""Approximate contraction: boundary-MPS with SVD truncation.

The reference lists approximate contraction as future work
(``book/src/future_work.md``); this module implements the standard
boundary-MPS scheme for 2-D grid networks (PEPS sandwiches): the top
row is an MPS, every interior row an MPO; after each MPS·MPO
application the boundary MPS is compressed to bond dimension ``chi``
by a QR canonicalization sweep followed by truncated SVDs. Memory and
time are then polynomial in ``chi`` instead of exponential in the grid
width — the classic accuracy-for-cost dial exact contraction lacks.

Scope notes:

- Sites may be connected by *several* parallel bonds (a PEPS sandwich
  has one bond per layer between neighbours); bonds per direction are
  fused into one dense axis, neighbours aligned by sorted leg id.
- The linear algebra runs through numpy at complex128 (QR/SVD of
  χ-sized matrices — planner-scale host work, like pathfinding; the
  contraction dial is what matters on TPU: pick ``chi`` so the exact
  *sliced* plan of the compressed network fits, or use the boundary
  value directly). A jitted fixed-``chi`` device sweep is the natural
  extension once shapes are frozen.
- ``collapse_peps_sandwich`` flattens the ``builders.peps`` sandwich
  (layer-major ordering, ``peps.rs:446-460`` equivalent) into the
  single-layer grid this module consumes.
"""

from __future__ import annotations

import functools as _functools
from typing import Sequence

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData


def _site_array(t: LeafTensor) -> np.ndarray:
    return np.asarray(t.data.into_data(), dtype=np.complex128).reshape(
        t.shape
    )


def _grouped(t: LeafTensor, groups: Sequence[Sequence[int]]) -> np.ndarray:
    """Dense site tensor with axes permuted/fused to the leg groups
    (one fused axis per group, legs within a group in the given order;
    missing groups become dim-1 axes)."""
    arr = _site_array(t)
    pos = {leg: i for i, leg in enumerate(t.legs)}
    perm: list[int] = []
    shape: list[int] = []
    for group in groups:
        size = 1
        for leg in group:
            perm.append(pos[leg])
            size *= t.bond_dims[pos[leg]]
        shape.append(size)
    if len(perm) != len(t.legs):
        raise ValueError(
            f"site tensor has legs {sorted(t.legs)} outside its grid "
            f"neighbourhood {sorted(l for g in groups for l in g)}"
        )
    return np.transpose(arr, perm).reshape(shape)


def _truncated_svd(m, chi: int, cutoff: float, xp=np):
    u, s, vh = xp.linalg.svd(m, full_matrices=False)
    if xp is np:
        keep = int(np.sum(s > cutoff * (s[0] if s.size else 1.0)))
        keep = max(1, min(keep, chi))
    else:
        # jitted path: the kept rank must be static, so the cut is by
        # chi alone (cutoff-based rank is value-dependent)
        keep = max(1, min(int(s.shape[0]), chi))
    return u[:, :keep], s[:keep], vh[:keep]


def _compress_mps(mps, chi: int, cutoff: float, xp=np):
    """Canonicalize left-to-right (QR), then truncate right-to-left
    (SVD). Tensors are (Dl, d, Dr)."""
    mps = list(mps)
    n = len(mps)
    # left-to-right QR: left-canonical form
    for i in range(n - 1):
        dl, d, dr = mps[i].shape
        q, r = xp.linalg.qr(mps[i].reshape(dl * d, dr))
        mps[i] = q.reshape(dl, d, q.shape[1])
        mps[i + 1] = xp.tensordot(r, mps[i + 1], axes=(1, 0))
    # right-to-left truncated SVD
    for i in range(n - 1, 0, -1):
        dl, d, dr = mps[i].shape
        u, s, vh = _truncated_svd(
            mps[i].reshape(dl, d * dr), chi, cutoff, xp
        )
        mps[i] = vh.reshape(vh.shape[0], d, dr)
        carry = u * s  # (dl, keep)
        mps[i - 1] = xp.tensordot(mps[i - 1], carry, axes=(2, 0))
    return mps


def _apply_mpo(mps, mpo, xp=np):
    """MPS (Dl, d_up, Dr) x MPO (Wl, Wr, d_up, d_down) →
    fat MPS (Dl·Wl, d_down, Dr·Wr)."""
    out = []
    for a, w in zip(mps, mpo):
        dl, dup, dr = a.shape
        wl, wr, wup, wdown = w.shape
        if dup != wup:
            raise ValueError(f"vertical bond mismatch: {dup} vs {wup}")
        t = xp.tensordot(a, w, axes=(1, 2))  # (dl, dr, wl, wr, wdown)
        t = xp.transpose(t, (0, 2, 4, 1, 3))  # (dl, wl, wdown, dr, wr)
        out.append(t.reshape(dl * wl, wdown, dr * wr))
    return out


def boundary_mps_contract(
    grid: Sequence[Sequence[LeafTensor]],
    chi: int,
    cutoff: float = 0.0,
    backend: str = "numpy",
) -> complex:
    """Contract a closed 2-D grid network approximately.

    ``grid[r][c]`` are data-carrying leaf tensors whose legs connect
    only to the four lattice neighbours (parallel bonds allowed, fused
    per direction). ``chi`` caps the boundary-MPS bond dimension; with
    ``chi`` at least the exact boundary rank the result is exact.

    ``backend="jax"`` runs the whole sweep as ONE jitted XLA program,
    explicitly pinned to the CPU platform (complex QR/SVD has no TPU
    lowering in this stack — the TPU execution path is split-complex):
    every intermediate shape is static given the grid, so the compiled
    program is cached per (shapes, chi) and reused across calls. The
    static-rank constraint means the value-dependent ``cutoff`` is
    numpy-only. (Platform discovery initializes all registered JAX
    plugins; on a host whose accelerator plugin wedges at init — the
    tunnel pathology in docs/running_on_tpu.md — pin
    ``jax.config.update("jax_platforms", "cpu")`` process-wide first,
    as everywhere else in this stack.)

    >>> import numpy as np
    >>> from tnc_tpu.builders.peps import peps
    >>> rng = np.random.default_rng(7)
    >>> tn = attach_random_data(peps(3, 3, 2, 2, 1), rng)
    >>> from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    >>> from tnc_tpu.tensornetwork.contraction import contract_tensor_network
    >>> path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    >>> want = complex(contract_tensor_network(tn, path,
    ...     backend="numpy").data.into_data().reshape(-1)[0])
    >>> grid = collapse_peps_sandwich(tn, 3, 3, 1)
    >>> got = boundary_mps_contract(grid, chi=4096)  # chi >= exact rank
    >>> abs(got - want) <= 1e-8 * max(1.0, abs(want))
    True
    """
    rows = len(grid)
    if rows < 2 or any(len(r) != len(grid[0]) for r in grid):
        raise ValueError("grid must be rectangular with >= 2 rows")
    cols = len(grid[0])
    if cols < 1:
        raise ValueError("grid rows must be non-empty")
    if chi < 1:
        raise ValueError("chi must be >= 1")
    if backend not in ("numpy", "jax"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend == "jax" and cutoff:
        raise ValueError(
            "cutoff-based rank is value-dependent; the jitted jax sweep "
            "supports chi truncation only"
        )

    legs_of = [[set(t.legs) for t in row] for row in grid]

    def shared(r1, c1, r2, c2) -> list[int]:
        if 0 <= r2 < rows and 0 <= c2 < cols:
            return sorted(legs_of[r1][c1] & legs_of[r2][c2])
        return []

    def groups(r, c):
        return (
            shared(r, c, r, c - 1),   # left
            shared(r, c, r, c + 1),   # right
            shared(r, c, r - 1, c),   # up
            shared(r, c, r + 1, c),   # down
        )

    def top_row():
        out = []
        for c in range(cols):
            left, right, up, down = groups(0, c)
            if up:
                raise ValueError("top row must have no upward bonds")
            out.append(_grouped(grid[0][c], (left, down, right)))
        return out

    def mid_rows():
        # lazy per row: only one interior row's dense grouped copies are
        # alive at a time on the numpy path
        for r in range(1, rows - 1):
            yield [_grouped(grid[r][c], groups(r, c)) for c in range(cols)]

    def bottom_row():
        out = []
        for c in range(cols):
            left, right, up, down = groups(rows - 1, c)
            if down:
                raise ValueError("bottom row must have no downward bonds")
            out.append(_grouped(grid[rows - 1][c], (left, up, right)))
        return out

    if backend == "jax":
        import jax

        # Complex QR/SVD only exists on CPU-like backends (the TPU path
        # of this stack is split-complex and has no complex dtypes), so
        # the sweep is pinned to the CPU platform explicitly — on an
        # accelerator-default environment the default device would be
        # the TPU and the program could not lower. One compiled program
        # per (shapes, chi), cached module-wide.
        cpu = jax.local_devices(backend="cpu")[0]
        dtype = (
            "complex128" if jax.config.read("jax_enable_x64") else "complex64"
        )
        with jax.default_device(cpu):
            fn = _jax_sweep_fn(chi)
            env = np.asarray(
                fn(
                    [jax.device_put(np.asarray(a, dtype=dtype), cpu)
                     for a in top_row()],
                    [
                        [jax.device_put(np.asarray(a, dtype=dtype), cpu)
                         for a in row]
                        for row in mid_rows()
                    ],
                    [jax.device_put(np.asarray(a, dtype=dtype), cpu)
                     for a in bottom_row()],
                )
            )
    else:
        env = _sweep(np, top_row(), mid_rows(), bottom_row(), chi, cutoff)
    if env.shape != (1, 1):
        raise ValueError("grid did not close to a scalar")
    return complex(env[0, 0])


def _sweep(xp, top, mid_rows, bottom, chi: int, cutoff: float):
    mps = list(top)
    for mpo in mid_rows:
        mps = _apply_mpo(mps, mpo, xp)
        mps = _compress_mps(mps, chi, cutoff, xp)
    env = xp.ones((1, 1), dtype=mps[0].dtype)
    for a, site in zip(mps, bottom):
        # env (Dl, Bl) · a (Dl, d, Dr) · site (Bl, d, Br) -> (Dr, Br)
        tmp = xp.tensordot(env, a, axes=(0, 0))  # (Bl, d, Dr)
        env = xp.tensordot(tmp, site, axes=((0, 1), (0, 1)))
    return env


@_functools.lru_cache(maxsize=16)
def _jax_sweep_fn(chi: int):
    """One jitted sweep per ``chi``; XLA's own cache then keys on the
    input shapes, so same-shape calls (chi sweeps over one grid, many
    grids of one geometry) compile once and reuse."""
    import jax
    import jax.numpy as jnp

    def run(top, mid, bottom):
        return _sweep(jnp, top, list(mid), bottom, chi, 0.0)

    return jax.jit(run)


def collapse_peps_sandwich(
    tn: CompositeTensor, length: int, depth: int, layers: int
) -> list[list[LeafTensor]]:
    """Flatten a ``builders.peps`` sandwich (data attached) into the
    single-layer ``depth × length`` grid ``boundary_mps_contract``
    consumes: each site's ``layers + 2`` stacked tensors are contracted
    over their vertical physical bonds (greedy local path), leaving the
    per-layer horizontal bonds as parallel grid bonds."""
    from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    from tnc_tpu.tensornetwork.contraction import contract_tensor_network

    n_layers = layers + 2
    leaves = list(tn.tensors)
    if len(leaves) != n_layers * depth * length:
        raise ValueError(
            f"expected {n_layers * depth * length} tensors "
            f"(layer-major peps ordering), got {len(leaves)}"
        )

    def site_index(k, r, c):
        return k * depth * length + r * length + c

    grid: list[list[LeafTensor]] = []
    for r in range(depth):
        row = []
        for c in range(length):
            stack = CompositeTensor(
                [leaves[site_index(k, r, c)].copy() for k in range(n_layers)]
            )
            result = Greedy(OptMethod.GREEDY).find_path(stack)
            merged = contract_tensor_network(
                stack, result.replace_path(), backend="numpy"
            )
            row.append(merged)
        grid.append(row)
    return grid


def attach_random_data(
    tn: CompositeTensor, rng: np.random.Generator, scale: float | None = None
) -> CompositeTensor:
    """Fill every metadata-only leaf with seeded complex Gaussian data
    (builder networks like ``peps`` are metadata-only); leaves that
    already carry data (gates, matrices, file refs) are left untouched.
    ``scale`` defaults to per-tensor ``1/sqrt(size)`` so contractions
    stay O(1)."""
    from tnc_tpu.tensornetwork.tensordata import DataKind

    for leaf in tn.tensors:
        if isinstance(leaf, CompositeTensor):
            attach_random_data(leaf, rng, scale)
            continue
        if leaf.data.kind is not DataKind.NONE:
            continue
        shape = leaf.shape
        s = scale if scale is not None else 1.0 / np.sqrt(
            max(1.0, float(np.prod(shape)))
        )
        data = (
            rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
        ) * s
        leaf.data = TensorData.matrix(data.astype(np.complex128))
    return tn
