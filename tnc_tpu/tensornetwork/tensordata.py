"""Lazy tensor data.

Mirror of ``tnc/src/tensornetwork/tensordata.rs:17-69``: tensor payloads are
symbolic until contraction touches them. A payload is one of

- ``NONE``   — metadata-only tensor (pathfinding, cost models)
- ``GATE``   — (name, angles, adjoint) resolved through the gate registry
- ``FILE``   — (path, tensor-id, adjoint) resolved through HDF5 loading
- ``MATRIX`` — an actual ndarray

``adjoint()`` is symbolic (flips a flag) except for ``MATRIX``, where it is
an eager conjugate-transpose (``tensordata.rs:59-69``).

Materialized data is ``numpy.complex128`` on host; the JAX executor moves
it to device (HBM) and optionally down-casts to ``complex64``.
"""

from __future__ import annotations

import enum
from typing import Any

import numpy as np


# Materialized tensor payload type (reference: ``DataTensor =
# ArrayD<Complex64>``, ``tensordata.rs:13``).
DataTensor = np.ndarray


class DataKind(enum.Enum):
    NONE = "none"
    GATE = "gate"
    FILE = "file"
    MATRIX = "matrix"


def matrix_transpose(data: np.ndarray) -> np.ndarray:
    """Transpose a matrix-like tensor of shape ``(2^n, 2^n)`` or split
    ``(2,2,...)`` by swapping the first half of dims with the second half
    (``gates.rs:83-101``).
    """
    if data.ndim <= 1:
        return data  # scalars and kets: the half-swap is the identity
    if data.ndim % 2:
        raise ValueError(f"matrix transpose needs an even ndim, got {data.ndim}")
    half = data.ndim // 2
    perm = tuple(range(half, data.ndim)) + tuple(range(half))
    return np.transpose(data, perm)


def matrix_adjoint(data: np.ndarray) -> np.ndarray:
    """Conjugate transpose with the half-dims-swap convention (``gates.rs:104-110``)."""
    return np.conj(matrix_transpose(data))


class TensorData:
    """Tagged union of lazy tensor payloads."""

    __slots__ = ("kind", "payload")

    def __init__(self, kind: DataKind, payload: Any) -> None:
        self.kind = kind
        self.payload = payload

    # -- constructors ------------------------------------------------------

    @classmethod
    def none(cls) -> "TensorData":
        return cls(DataKind.NONE, None)

    @classmethod
    def gate(cls, name: str, angles: tuple[float, ...] = (), adjoint: bool = False) -> "TensorData":
        """Lazy named-gate payload (materialized via the gate library).

        >>> import numpy as np
        >>> TensorData.gate("h").into_data().shape
        (2, 2)
        >>> x = TensorData.gate("x")
        >>> np.allclose(x.adjoint().into_data(), x.into_data())  # X is Hermitian
        True
        """
        return cls(DataKind.GATE, (name, tuple(angles), adjoint))

    @classmethod
    def file(cls, path: str, tensor_id: int, adjoint: bool = False) -> "TensorData":
        return cls(DataKind.FILE, (path, tensor_id, adjoint))

    @classmethod
    def matrix(cls, array: np.ndarray) -> "TensorData":
        return cls(DataKind.MATRIX, np.asarray(array, dtype=np.complex128))

    @classmethod
    def from_values(cls, shape: tuple[int, ...], values: list[complex]) -> "TensorData":
        return cls.matrix(np.asarray(values, dtype=np.complex128).reshape(shape))

    # -- queries -----------------------------------------------------------

    def is_none(self) -> bool:
        return self.kind is DataKind.NONE

    # -- lazy resolution ---------------------------------------------------

    def into_data(self) -> np.ndarray:
        """Materialize to a complex128 ndarray (``tensordata.rs:37-56``)."""
        if self.kind is DataKind.MATRIX:
            return self.payload
        if self.kind is DataKind.GATE:
            from tnc_tpu.gates import load_gate, load_gate_adjoint

            name, angles, adj = self.payload
            return load_gate_adjoint(name, angles) if adj else load_gate(name, angles)
        if self.kind is DataKind.FILE:
            from tnc_tpu.io.hdf5 import load_data

            path, tensor_id, adj = self.payload
            data = load_data(path, tensor_id)
            return matrix_adjoint(data) if adj else data
        raise ValueError("Cannot materialize TensorData.none()")

    def adjoint(self) -> "TensorData":
        """Symbolic adjoint: flip the flag; eager only for MATRIX
        (``tensordata.rs:59-69``).
        """
        if self.kind is DataKind.MATRIX:
            return TensorData.matrix(matrix_adjoint(self.payload))
        if self.kind is DataKind.GATE:
            name, angles, adj = self.payload
            return TensorData(DataKind.GATE, (name, angles, not adj))
        if self.kind is DataKind.FILE:
            path, tensor_id, adj = self.payload
            return TensorData(DataKind.FILE, (path, tensor_id, not adj))
        return TensorData.none()

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, TensorData):
            return NotImplemented
        if self.kind is not other.kind:
            return False
        if self.kind is DataKind.MATRIX:
            return bool(np.array_equal(self.payload, other.payload))
        return self.payload == other.payload

    def __repr__(self) -> str:
        if self.kind is DataKind.MATRIX:
            return f"TensorData.matrix(shape={self.payload.shape})"
        return f"TensorData.{self.kind.value}({self.payload})"
