"""HDF5 tensor and tensor-network IO.

Mirror of ``tnc/src/io/hdf5.rs:3-67``: file schema is a group ``/tensors``
with one dataset per tensor named by its tensor id, each carrying a
``bids`` attribute listing its leg (bond) ids. A dataset named ``-1``
holds an output tensor and is skipped when loading a network.
"""

from __future__ import annotations

import contextlib

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData

TENSORS_GROUP = "tensors"
OUTPUT_TENSOR_NAME = "-1"


def memory_file(name: str | None = None):
    """An in-memory core-backed HDF5 file (no disk IO) — the reference's
    test-fixture style (``hdf5.rs:119-124``, ``FileAccessProperties``
    with a core driver and no backing store). Pass the returned handle
    anywhere a path is accepted.

    >>> import numpy as np
    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> f = memory_file()
    >>> t = LeafTensor([0, 1], [2, 2],
    ...     TensorData.matrix(np.eye(2, dtype=np.complex128)))
    >>> store_data(f, 0, t)
    >>> np.allclose(load_data(f, 0), np.eye(2))
    True
    >>> f.close()
    """
    import uuid

    import h5py

    # HDF5 tracks open files by name even for the core driver, so a
    # fixed default would make a second concurrent in-memory file fail
    if name is None:
        name = f"tnc-mem-{uuid.uuid4().hex}.h5"
    return h5py.File(name, "w", driver="core", backing_store=False)


@contextlib.contextmanager
def _open(src, mode: str):
    """Accept a path (opened/closed here) or an already-open h5py.File
    (left open for the caller)."""
    import h5py

    if isinstance(src, h5py.File):
        yield src
    else:
        with h5py.File(src, mode) as f:
            yield f


def roundtrip_example():
    """Store/load a tensor through the reference HDF5 schema.

    >>> import tempfile, os, numpy as np
    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> path = os.path.join(tempfile.mkdtemp(), "t.h5")
    >>> t = LeafTensor([0, 1], [2, 2],
    ...     TensorData.matrix(np.eye(2, dtype=np.complex128)))
    >>> store_data(path, 0, t)
    >>> np.allclose(load_data(path, 0), np.eye(2))
    True
    """


def load_data(path, tensor_id: int) -> np.ndarray:
    """Load a single tensor's data (``hdf5.rs:26-38`` load_data).
    ``path`` may be a filename or an open ``h5py.File``."""
    with _open(path, "r") as f:
        dataset = f[TENSORS_GROUP][str(tensor_id)]
        return np.asarray(dataset[()], dtype=np.complex128)


def load_tensor(path, lazy: bool = True) -> CompositeTensor:
    """Load a whole tensor network (``hdf5.rs:40-50`` load_tensor).

    With ``lazy`` (default), leaf data stays a FILE reference and is
    materialized at contraction time, matching the reference's lazy
    ``TensorData::File``. ``path`` may be a filename or an open
    ``h5py.File``; in-memory files have no filename for a lazy
    reference to point at, so they always load eagerly.
    """
    import h5py

    if isinstance(path, h5py.File):
        lazy = False  # nothing durable for a FILE reference to resolve
    tensors: list[LeafTensor] = []
    with _open(path, "r") as f:
        group = f[TENSORS_GROUP]
        for name in sorted(group, key=lambda s: int(s)):
            if name == OUTPUT_TENSOR_NAME:
                continue
            dataset = group[name]
            legs = [int(b) for b in dataset.attrs["bids"]]
            shape = list(dataset.shape)
            if len(legs) != len(shape):
                raise ValueError(
                    f"tensor {name}: {len(legs)} leg ids but rank {len(shape)}"
                )
            data = (
                TensorData.file(path, int(name))
                if lazy
                else TensorData.matrix(np.asarray(dataset[()], dtype=np.complex128))
            )
            tensors.append(LeafTensor(legs, shape, data))
    return CompositeTensor(tensors)


def store_data(path, tensor_id: int, tensor: LeafTensor) -> None:
    """Store a single tensor (``hdf5.rs:52-67`` store_data).
    ``path`` may be a filename or an open ``h5py.File``."""
    data = tensor.data.into_data()
    with _open(path, "a") as f:
        group = f.require_group(TENSORS_GROUP)
        name = str(tensor_id)
        if name in group:
            del group[name]
        dataset = group.create_dataset(name, data=data)
        dataset.attrs["bids"] = np.asarray(tensor.legs, dtype=np.int64)
