"""HDF5 tensor and tensor-network IO.

Mirror of ``tnc/src/io/hdf5.rs:3-67``: file schema is a group ``/tensors``
with one dataset per tensor named by its tensor id, each carrying a
``bids`` attribute listing its leg (bond) ids. A dataset named ``-1``
holds an output tensor and is skipped when loading a network.
"""

from __future__ import annotations

import numpy as np

from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
from tnc_tpu.tensornetwork.tensordata import TensorData

TENSORS_GROUP = "tensors"
OUTPUT_TENSOR_NAME = "-1"


def roundtrip_example():
    """Store/load a tensor through the reference HDF5 schema.

    >>> import tempfile, os, numpy as np
    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> path = os.path.join(tempfile.mkdtemp(), "t.h5")
    >>> t = LeafTensor([0, 1], [2, 2],
    ...     TensorData.matrix(np.eye(2, dtype=np.complex128)))
    >>> store_data(path, 0, t)
    >>> np.allclose(load_data(path, 0), np.eye(2))
    True
    """


def load_data(path: str, tensor_id: int) -> np.ndarray:
    """Load a single tensor's data (``hdf5.rs:26-38`` load_data)."""
    import h5py

    with h5py.File(path, "r") as f:
        dataset = f[TENSORS_GROUP][str(tensor_id)]
        return np.asarray(dataset[()], dtype=np.complex128)


def load_tensor(path: str, lazy: bool = True) -> CompositeTensor:
    """Load a whole tensor network (``hdf5.rs:40-50`` load_tensor).

    With ``lazy`` (default), leaf data stays a FILE reference and is
    materialized at contraction time, matching the reference's lazy
    ``TensorData::File``.
    """
    import h5py

    tensors: list[LeafTensor] = []
    with h5py.File(path, "r") as f:
        group = f[TENSORS_GROUP]
        for name in sorted(group, key=lambda s: int(s)):
            if name == OUTPUT_TENSOR_NAME:
                continue
            dataset = group[name]
            legs = [int(b) for b in dataset.attrs["bids"]]
            shape = list(dataset.shape)
            if len(legs) != len(shape):
                raise ValueError(
                    f"tensor {name}: {len(legs)} leg ids but rank {len(shape)}"
                )
            data = (
                TensorData.file(path, int(name))
                if lazy
                else TensorData.matrix(np.asarray(dataset[()], dtype=np.complex128))
            )
            tensors.append(LeafTensor(legs, shape, data))
    return CompositeTensor(tensors)


def store_data(path: str, tensor_id: int, tensor: LeafTensor) -> None:
    """Store a single tensor (``hdf5.rs:52-67`` store_data)."""
    import h5py

    data = tensor.data.into_data()
    with h5py.File(path, "a") as f:
        group = f.require_group(TENSORS_GROUP)
        name = str(tensor_id)
        if name in group:
            del group[name]
        dataset = group.create_dataset(name, data=data)
        dataset.attrs["bids"] = np.asarray(tensor.legs, dtype=np.int64)
