from tnc_tpu.io.qasm.importer import import_qasm  # noqa: F401
