"""OpenQASM 2.0 grammar (lark).

Replaces the reference's ANTLR-generated lexer/parser
(``tnc/src/io/qasm/generated``, ~5.7k generated LoC) with a compact lark
grammar covering the same supported subset: version header, includes,
register declarations, gate declarations, gate calls (incl. the ``U`` and
``CX`` primitives), ``barrier``; ``measure``/``reset``/``if`` are parsed
so the importer can reject them with a clear error
(``qasm_importer.rs:10-11``).
"""

# >>> doctest: the grammar parses a minimal program (see module tests)
def parse_example():
    """
    >>> import lark
    >>> parser = lark.Lark(QASM2_GRAMMAR, parser="lalr")
    >>> tree = parser.parse('OPENQASM 2.0; qreg q[2]; CX q[0], q[1];')
    >>> [st.data for st in tree.children]
    [Token('RULE', 'version'), Token('RULE', 'statement'), Token('RULE', 'statement')]
    """


QASM2_GRAMMAR = r"""
start: version? statement*

version: "OPENQASM" REAL_OR_INT ";"

statement: include_stmt
         | qreg_decl
         | creg_decl
         | gate_decl
         | opaque_decl
         | gate_call
         | barrier_stmt
         | measure_stmt
         | reset_stmt
         | if_stmt

include_stmt: "include" ESCAPED_STRING ";"
qreg_decl: "qreg" CNAME "[" INT "]" ";"
creg_decl: "creg" CNAME "[" INT "]" ";"

gate_decl: "gate" CNAME gate_params? id_list "{" gate_body "}"
gate_params: "(" [param_list] ")"
param_list: CNAME ("," CNAME)*
id_list: CNAME ("," CNAME)*
gate_body: (gate_call | barrier_stmt)*

opaque_decl: "opaque" CNAME gate_params? id_list ";"

gate_call: gate_name call_args? argument_list ";"
gate_name: CNAME | UGATE | CXGATE
UGATE: "U"
CXGATE: "CX"
call_args: "(" [expr_list] ")"
expr_list: expr ("," expr)*
argument_list: argument ("," argument)*
argument: CNAME ("[" INT "]")?

barrier_stmt: "barrier" argument_list ";"
measure_stmt: "measure" argument "->" argument ";"
reset_stmt: "reset" argument ";"
if_stmt: "if" "(" CNAME "==" INT ")" gate_call

?expr: term
     | expr "+" term -> add
     | expr "-" term -> sub
?term: factor
     | term "*" factor -> mul
     | term "/" factor -> div
?factor: power
       | "-" factor -> neg
       | "+" factor
?power: atom
      | atom "^" factor -> pow
?atom: REAL_OR_INT -> number
     | PI -> pi
     | CNAME -> name
     | FUNC "(" expr ")" -> func
     | "(" expr ")"

PI: "pi"
FUNC: "sin" | "cos" | "tan" | "exp" | "ln" | "sqrt"
REAL_OR_INT: /\d+\.\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|\d+([eE][+-]?\d+)?/
INT: /\d+/

COMMENT: /\/\/[^\n]*/
%import common.CNAME
%import common.ESCAPED_STRING
%import common.WS
%ignore WS
%ignore COMMENT
"""
