"""OpenQASM 2.0 → Circuit importer.

Equivalent of the reference pipeline ``import_qasm``
(``tnc/src/io/qasm/qasm_importer.rs:13-38``): include expansion (with the
standard ``qelib1.inc`` embedded), parse, constant folding, gate inlining
down to registry built-ins, and circuit creation with QASM register
broadcasting (``circuit_creator.rs:16-58``).

Where the reference runs four separate AST passes (fold → inline → fold →
create), this importer evaluates recursively: user-defined gate calls are
expanded with a numeric parameter environment, so folding happens
naturally at substitution time. A gate call whose (lowercased) name is in
the gate registry is emitted directly and never inlined, matching the
reference's ``is_gate_known`` check (``ast.rs:328``).

Unsupported (as in the reference): ``measure``, ``reset``, ``if``,
classical ops. ``barrier`` is a no-op.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from pathlib import Path

from lark import Lark, Token, Tree

from tnc_tpu.builders.circuit_builder import Circuit, Qubit
from tnc_tpu.gates import gate_arity, is_gate_known
from tnc_tpu.io.qasm.grammar import QASM2_GRAMMAR
from tnc_tpu.io.qasm.qelib1 import QELIB1
from tnc_tpu.tensornetwork.tensordata import TensorData


class QasmError(ValueError):
    """Raised on unsupported or malformed QASM input."""


_PARSER: Lark | None = None


def _parser() -> Lark:
    global _PARSER
    if _PARSER is None:
        _PARSER = Lark(QASM2_GRAMMAR, parser="lalr", lexer="contextual")
    return _PARSER


_FUNCS = {
    "sin": math.sin,
    "cos": math.cos,
    "tan": math.tan,
    "exp": math.exp,
    "ln": math.log,
    "sqrt": math.sqrt,
}


def _eval_expr(node, env: dict[str, float]) -> float:
    """Numeric evaluation of a parameter expression (replaces the
    reference's ``ExpressionFolder``)."""
    if isinstance(node, Token):
        return float(node)
    data = node.data
    kids = node.children
    if data == "number":
        return float(kids[0])
    if data == "pi":
        return math.pi
    if data == "name":
        name = str(kids[0])
        if name not in env:
            raise QasmError(f"Unknown parameter '{name}' in expression")
        return env[name]
    if data == "func":
        return _FUNCS[str(kids[0])](_eval_expr(kids[1], env))
    if data == "add":
        return _eval_expr(kids[0], env) + _eval_expr(kids[1], env)
    if data == "sub":
        return _eval_expr(kids[0], env) - _eval_expr(kids[1], env)
    if data == "mul":
        return _eval_expr(kids[0], env) * _eval_expr(kids[1], env)
    if data == "div":
        return _eval_expr(kids[0], env) / _eval_expr(kids[1], env)
    if data == "neg":
        return -_eval_expr(kids[0], env)
    if data == "pow":
        return _eval_expr(kids[0], env) ** _eval_expr(kids[1], env)
    raise QasmError(f"Unsupported expression node '{data}'")


@dataclass
class _GateDef:
    params: list[str]
    qubit_args: list[str]
    body: list  # gate_call trees


class _Importer:
    def __init__(self, include_dir: Path | None = None) -> None:
        self.circuit = Circuit()
        self.registers: dict[str, object] = {}
        self.gate_defs: dict[str, _GateDef] = {}
        self.include_dir = include_dir

    # -- include expansion (include_resolver.rs) ----------------------------

    def expand_includes(self, code: str, depth: int = 0) -> str:
        if depth > 16:
            raise QasmError("Include depth exceeded (cycle?)")
        out_lines = []
        for line in code.splitlines():
            stripped = line.strip()
            if stripped.startswith("include"):
                path = stripped.split('"')[1]
                if path == "qelib1.inc":
                    included = QELIB1
                else:
                    if self.include_dir is None:
                        raise QasmError(
                            f"Cannot resolve include '{path}' without an include dir"
                        )
                    included = (self.include_dir / path).read_text()
                out_lines.append(self.expand_includes(included, depth + 1))
            else:
                out_lines.append(line)
        return "\n".join(out_lines)

    # -- statement handling -------------------------------------------------

    def run(self, code: str) -> Circuit:
        code = self.expand_includes(code)
        try:
            tree = _parser().parse(code)
        except Exception as exc:  # lark parse/lex errors -> QasmError
            raise QasmError(f"QASM parse error: {exc}") from exc
        for stmt in tree.children:
            if isinstance(stmt, Tree) and stmt.data == "version":
                continue
            self._statement(stmt.children[0])
        return self.circuit

    def _statement(self, node: Tree) -> None:
        data = node.data
        if data == "include_stmt":
            raise QasmError("Unexpanded include found after expansion")
        if data == "qreg_decl":
            name, size = str(node.children[0]), int(node.children[1])
            if name in self.registers:
                raise QasmError(f"Register '{name}' redeclared")
            self.registers[name] = self.circuit.allocate_register(size)
            return
        if data == "creg_decl":
            return  # tolerated, unused
        if data == "gate_decl":
            self._gate_decl(node)
            return
        if data == "opaque_decl":
            name = str(node.children[0])
            if not is_gate_known(name.lower()):
                raise QasmError(f"Opaque gate '{name}' is not a known gate")
            return
        if data == "gate_call":
            self._toplevel_gate_call(node)
            return
        if data == "barrier_stmt":
            return
        if data in ("measure_stmt", "reset_stmt", "if_stmt"):
            keyword = data.split("_")[0]
            raise QasmError(f"'{keyword}' is not supported")
        raise QasmError(f"Unsupported statement '{data}'")

    def _gate_decl(self, node: Tree) -> None:
        name = str(node.children[0])
        idx = 1
        params: list[str] = []
        if isinstance(node.children[idx], Tree) and node.children[idx].data == "gate_params":
            inner = node.children[idx].children
            if inner and inner[0] is not None:
                params = [str(t) for t in inner[0].children]
            idx += 1
        qubit_args = [str(t) for t in node.children[idx].children]
        body_node = node.children[idx + 1]
        body = [c for c in body_node.children if c.data == "gate_call"]
        self.gate_defs[name] = _GateDef(params, qubit_args, body)

    # -- gate call resolution (gate_inliner.rs + circuit_creator.rs) --------

    @staticmethod
    def _call_parts(node: Tree) -> tuple[str, list, list[Tree]]:
        name = str(node.children[0].children[0])
        idx = 1
        exprs: list = []
        if (
            idx < len(node.children)
            and isinstance(node.children[idx], Tree)
            and node.children[idx].data == "call_args"
        ):
            inner = node.children[idx].children
            if inner and inner[0] is not None:
                exprs = list(inner[0].children)
            idx += 1
        args = list(node.children[idx].children)
        return name, exprs, args

    def _toplevel_gate_call(self, node: Tree) -> None:
        name, exprs, args = self._call_parts(node)
        angles = [_eval_expr(e, {}) for e in exprs]

        # QASM broadcasting: full-register args apply the gate per element
        # (``circuit_creator.rs`` broadcast semantics).
        resolved: list[list[Qubit]] = []
        broadcast_len: int | None = None
        for arg in args:
            reg_name = str(arg.children[0])
            if reg_name not in self.registers:
                raise QasmError(f"Unknown register '{reg_name}'")
            register = self.registers[reg_name]
            if len(arg.children) > 1 and arg.children[1] is not None:
                resolved.append([register.qubit(int(arg.children[1]))])
            else:
                resolved.append(list(register.qubits()))
                if broadcast_len is None:
                    broadcast_len = len(register)
                elif broadcast_len != len(register):
                    raise QasmError("Mismatched register sizes in broadcast")

        n = broadcast_len if broadcast_len is not None else 1
        for k in range(n):
            qubits = [(qs[0] if len(qs) == 1 else qs[k]) for qs in resolved]
            self._apply(name, angles, qubits)

    def _apply(
        self, name: str, angles: list[float], qubits: list[Qubit], depth: int = 0
    ) -> None:
        if depth > 64:
            raise QasmError(
                f"Gate inlining exceeded depth 64 at '{name}' (recursive definition?)"
            )
        lname = name.lower()
        if is_gate_known(lname):
            arity = gate_arity(lname)
            if arity is not None and arity != len(qubits):
                raise QasmError(
                    f"Gate '{name}' expects {arity} qubits, got {len(qubits)}"
                )
            self.circuit.append_gate(TensorData.gate(lname, tuple(angles)), qubits)
            return
        if name not in self.gate_defs:
            raise QasmError(f"Unknown gate '{name}'")
        gate = self.gate_defs[name]
        if len(gate.params) != len(angles):
            raise QasmError(
                f"Gate '{name}' expects {len(gate.params)} params, got {len(angles)}"
            )
        if len(gate.qubit_args) != len(qubits):
            raise QasmError(
                f"Gate '{name}' expects {len(gate.qubit_args)} qubits, got {len(qubits)}"
            )
        env = dict(zip(gate.params, angles))
        qubit_env = dict(zip(gate.qubit_args, qubits))
        for call in gate.body:
            sub_name, sub_exprs, sub_args = self._call_parts(call)
            sub_angles = [_eval_expr(e, env) for e in sub_exprs]
            sub_qubits = []
            for arg in sub_args:
                qname = str(arg.children[0])
                if qname not in qubit_env:
                    raise QasmError(f"Unknown qubit '{qname}' in gate '{name}'")
                sub_qubits.append(qubit_env[qname])
            self._apply(sub_name, sub_angles, sub_qubits, depth + 1)


def import_qasm(code: str, include_dir: str | Path | None = None) -> Circuit:
    """Create a :class:`Circuit` from OpenQASM 2.0 source.

    >>> c = import_qasm('''OPENQASM 2.0;
    ... include "qelib1.inc";
    ... qreg q[2];
    ... h q[0];
    ... cx q[0], q[1];''')
    >>> tn, _ = c.into_statevector_network()
    >>> len(tn)   # 2 kets + 2 gates
    4
    """
    importer = _Importer(Path(include_dir) if include_dir else None)
    return importer.run(code)
