"""The standard OpenQASM 2.0 library header (qelib1.inc).

This is the public Quantum-Experience standard header shipped with
OpenQASM 2.0 (embedded the same way the reference embeds it,
``include_resolver.rs:16``).
"""

def qelib1_example():
    """The embedded standard header defines the usual gate set.

    >>> "gate h a" in QELIB1 and "gate cx c,t" in QELIB1
    True
    >>> from tnc_tpu.io.qasm import import_qasm
    >>> c = import_qasm(
    ...     'OPENQASM 2.0;\\ninclude "qelib1.inc";\\nqreg q[1];\\nh q[0];')
    >>> len(c.tensor_network)   # |0> ket + the h gate tensor
    2
    """


QELIB1 = r"""
// Quantum Experience (QE) Standard Header
// file: qelib1.inc

// --- QE Hardware primitives ---

// 3-parameter 2-pulse single qubit gate
gate u3(theta,phi,lambda) q { U(theta,phi,lambda) q; }
// 2-parameter 1-pulse single qubit gate
gate u2(phi,lambda) q { U(pi/2,phi,lambda) q; }
// 1-parameter 0-pulse single qubit gate
gate u1(lambda) q { U(0,0,lambda) q; }
// controlled-NOT
gate cx c,t { CX c,t; }
// idle gate (identity)
gate id a { U(0,0,0) a; }
// idle gate (identity) with length gamma*sqglen
gate u0(gamma) q { U(0,0,0) q; }

// --- QE Standard Gates ---

// generic single qubit gate
gate u(theta,phi,lambda) q { U(theta,phi,lambda) q; }
// phase gate
gate p(lambda) q { U(0,0,lambda) q; }
// Pauli gate: bit-flip
gate x a { u3(pi,0,pi) a; }
// Pauli gate: bit and phase flip
gate y a { u3(pi,pi/2,pi/2) a; }
// Pauli gate: phase flip
gate z a { u1(pi) a; }
// Clifford gate: Hadamard
gate h a { u2(0,pi) a; }
// Clifford gate: sqrt(Z) phase gate
gate s a { u1(pi/2) a; }
// Clifford gate: conjugate of sqrt(Z)
gate sdg a { u1(-pi/2) a; }
// C3 gate: sqrt(S) phase gate
gate t a { u1(pi/4) a; }
// C3 gate: conjugate of sqrt(S)
gate tdg a { u1(-pi/4) a; }

// --- Standard rotations ---
// Rotation around X-axis
gate rx(theta) a { u3(theta, -pi/2,pi/2) a; }
// rotation around Y-axis
gate ry(theta) a { u3(theta,0,0) a; }
// rotation around Z axis
gate rz(phi) a { u1(phi) a; }

// --- QE Standard User-Defined Gates  ---

// sqrt(X)
gate sx a { sdg a; h a; sdg a; }
// inverse sqrt(X)
gate sxdg a { s a; h a; s a; }
// controlled-Phase
gate cz a,b { h b; cx a,b; h b; }
// controlled-Y
gate cy a,b { sdg b; cx a,b; s b; }
// swap
gate swap a,b { cx a,b; cx b,a; cx a,b; }
// controlled-H
gate ch a,b {
h b; sdg b;
cx a,b;
h b; t b;
cx a,b;
t b; h b; s b; x b; s a;
}
// C3 gate: Toffoli
gate ccx a,b,c
{
  h c;
  cx b,c; tdg c;
  cx a,c; t c;
  cx b,c; tdg c;
  cx a,c; t b; t c; h c;
  cx a,b; t a; tdg b;
  cx a,b;
}
// cswap (Fredkin)
gate cswap a,b,c
{
  cx c,b;
  ccx a,b,c;
  cx c,b;
}
// controlled rx rotation
gate crx(lambda) a,b
{
  u1(pi/2) b;
  cx a,b;
  u3(-lambda/2,0,0) b;
  cx a,b;
  u3(lambda/2,-pi/2,0) b;
}
// controlled ry rotation
gate cry(lambda) a,b
{
  ry(lambda/2) b;
  cx a,b;
  ry(-lambda/2) b;
  cx a,b;
}
// controlled rz rotation
gate crz(lambda) a,b
{
  rz(lambda/2) b;
  cx a,b;
  rz(-lambda/2) b;
  cx a,b;
}
// controlled phase rotation
gate cu1(lambda) a,b
{
  u1(lambda/2) a;
  cx a,b;
  u1(-lambda/2) b;
  cx a,b;
  u1(lambda/2) b;
}
gate cp(lambda) a,b
{
  p(lambda/2) a;
  cx a,b;
  p(-lambda/2) b;
  cx a,b;
  p(lambda/2) b;
}
// controlled-U
gate cu3(theta,phi,lambda) c, t
{
  // implements controlled-U(theta,phi,lambda) with  target t and control c
  u1((lambda+phi)/2) c;
  u1((lambda-phi)/2) t;
  cx c,t;
  u3(-theta/2,0,-(phi+lambda)/2) t;
  cx c,t;
  u3(theta/2,phi,0) t;
}
// controlled-sqrt(X)
gate csx a,b { h b; cu1(pi/2) a,b; h b; }
// controlled-U gate
gate cu(theta,phi,lambda,gamma) c, t
{ p(gamma) c;
  p((lambda+phi)/2) c;
  p((lambda-phi)/2) t;
  cx c,t;
  u(-theta/2,0,-(phi+lambda)/2) t;
  cx c,t;
  u(theta/2,phi,0) t;
}
// two-qubit XX rotation
gate rxx(theta) a,b
{
  u3(pi/2, theta, 0) a;
  h b;
  cx a,b;
  u1(-theta) b;
  cx a,b;
  h b;
  u2(-pi, pi-theta) a;
}
// two-qubit ZZ rotation
gate rzz(theta) a,b
{
  cx a,b;
  u1(theta) b;
  cx a,b;
}
// relative-phase CCX
gate rccx a,b,c
{
  u2(0,pi) c;
  u1(pi/4) c;
  cx b, c;
  u1(-pi/4) c;
  cx a, c;
  u1(pi/4) c;
  cx b, c;
  u1(-pi/4) c;
  u2(0,pi) c;
}
// relative-phase 3-controlled X gate
gate rc3x a,b,c,d
{
  u2(0,pi) d;
  u1(pi/4) d;
  cx c,d;
  u1(-pi/4) d;
  u2(0,pi) d;
  cx a,d;
  u1(pi/4) d;
  cx b,d;
  u1(-pi/4) d;
  cx a,d;
  u1(pi/4) d;
  cx b,d;
  u1(-pi/4) d;
  u2(0,pi) d;
  u1(pi/4) d;
  cx c,d;
  u1(-pi/4) d;
  u2(0,pi) d;
}
// 3-controlled X gate
gate c3x a,b,c,d
{
    h d;
    p(pi/8) a;
    p(pi/8) b;
    p(pi/8) c;
    p(pi/8) d;
    cx a, b;
    p(-pi/8) b;
    cx a, b;
    cx b, c;
    p(-pi/8) c;
    cx a, c;
    p(pi/8) c;
    cx b, c;
    p(-pi/8) c;
    cx a, c;
    cx c, d;
    p(-pi/8) d;
    cx b, d;
    p(pi/8) d;
    cx c, d;
    p(-pi/8) d;
    cx a, d;
    p(pi/8) d;
    cx c, d;
    p(-pi/8) d;
    cx b, d;
    p(pi/8) d;
    cx c, d;
    p(-pi/8) d;
    cx a, d;
    h d;
}
// 3-controlled sqrt(X) gate, this equals the C3X gate where the CU1 rotations are -pi/8 not -pi/4
gate c3sqrtx a,b,c,d
{
    h d; cu1(pi/8) a,d; h d;
    cx a,b;
    h d; cu1(-pi/8) b,d; h d;
    cx a,b;
    h d; cu1(pi/8) b,d; h d;
    cx b,c;
    h d; cu1(-pi/8) c,d; h d;
    cx a,c;
    h d; cu1(pi/8) c,d; h d;
    cx b,c;
    h d; cu1(-pi/8) c,d; h d;
    cx a,c;
    h d; cu1(pi/8) c,d; h d;
}
// 4-controlled X gate
gate c4x a,b,c,d,e
{
    h e; cu1(pi/2) d,e; h e;
    c3x a,b,c,d;
    h e; cu1(-pi/2) d,e; h e;
    c3x a,b,c,d;
    c3sqrtx a,b,c,e;
}

"""
