from tnc_tpu.io.hdf5 import load_data, load_tensor, store_data  # noqa: F401
