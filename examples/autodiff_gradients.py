"""Differentiable contraction: variational-gradient workflows.

A capability the Rust reference cannot offer: gradients of an
expectation value w.r.t. gate parameters from ONE reverse-mode sweep
through the same compiled program the forward pass runs — no
parameter-shift re-contractions. Shown three ways: whole program,
sliced plan (gradient memory stays at the sliced peak), and a batched
amplitude sweep.

Run:  python examples/autodiff_gradients.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")  # gradient dtype is complex
jax.config.update("jax_enable_x64", True)  # complex128 end to end

import numpy as np

from tnc_tpu.builders.circuit_builder import Circuit
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.ops.autodiff import contraction_value_and_grad
from tnc_tpu.ops.program import flat_leaf_tensors
from tnc_tpu.tensornetwork.sweep import amplitude_sweep_value_and_grad
from tnc_tpu.tensornetwork.tensordata import DataKind, TensorData

# -- d<Z>/dθ of ⟨0|Rx(θ)† Z Rx(θ)|0⟩ = -sin(θ) ---------------------------
theta = 0.7
c = Circuit()
reg = c.allocate_register(1)
c.append_gate(TensorData.gate("rx", [theta]), [reg.qubit(0)])
tn = c.into_expectation_value_network()
path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()

# the Rx gate leaves are the differentiable parameters
slots = [
    i
    for i, leaf in enumerate(flat_leaf_tensors(tn))
    if leaf.data.kind is DataKind.GATE and leaf.data.payload[0] == "rx"
]
value, grads = contraction_value_and_grad(tn, path, wrt=slots, dtype="complex128")
print(f"<Z> = {value.reshape(-1)[0].real:+.6f}   (cos θ = {np.cos(theta):+.6f})")

# chain rule through the gate's θ-derivative gives d<Z>/dθ
eps = 1e-7
from tnc_tpu.gates import load_gate, load_gate_adjoint

total = 0.0
for slot, g in zip(slots, grads):
    leaf = flat_leaf_tensors(tn)[slot]
    name, angles, adj = leaf.data.payload
    load = load_gate_adjoint if adj else load_gate
    dgate = (load(name, [theta + eps]) - load(name, [theta - eps])) / (2 * eps)
    contrib = np.sum(g * dgate.reshape(g.shape)).real
    print(f"  slot {slot}: dθ contribution {contrib:+.6f}")
    total += contrib
print(f"d<Z>/dθ = {total:+.6f}   (-sin θ = {-np.sin(theta):+.6f})")
assert abs(total + np.sin(theta)) < 1e-5

# -- gradient of batch probability mass over an amplitude sweep ----------
c2 = Circuit()
reg2 = c2.allocate_register(3)
c2.append_gate(TensorData.gate("h"), [reg2.qubit(0)])
c2.append_gate(TensorData.gate("cx"), [reg2.qubit(0), reg2.qubit(1)])
c2.append_gate(TensorData.gate("ry", [0.3]), [reg2.qubit(2)])
amps, sweep_grads = amplitude_sweep_value_and_grad(
    c2, ["000", "110", "111"], dtype="complex128"
)
print(f"sweep amplitudes: {np.round(amps, 4)}")
print(f"sum |amp|^2 = {float(np.sum(np.abs(amps) ** 2)):.6f}; "
      f"{len(sweep_grads)} leaf gradients computed in one reverse sweep")
