"""Partitioning × slicing: the composition the reference lists as future
work (``book/src/future_work.md`` item 2: "Slicing is currently not
supported, as it is not easy to combine it with partitioning").

Legs are sliced across the whole network — including partition cut
edges, which shrinks the externals that dominate partition memory — and
for every slice index each device contracts its partition concurrently,
the fan-in schedule reduces over the devices, and the results accumulate
on the root device. This is BASELINE config #5's pipeline at toy scale.

Run (8-device virtual CPU mesh):

  TNC_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/sliced_partitioning.py
"""

import os
import random
import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("TNC_TPU_PLATFORM") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from tnc_tpu.builders.sycamore_circuit import sycamore_circuit
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.contractionpath.repartitioning import compute_solution
from tnc_tpu.parallel.partitioned import (
    distributed_partitioned_sliced_contraction,
)
from tnc_tpu.tensornetwork.contraction import contract_tensor_network
from tnc_tpu.tensornetwork.partitioning import find_partitioning
from tnc_tpu.tensornetwork.simplify import simplify_network


def main() -> None:
    import jax

    n_devices = min(4, len(jax.devices()))

    rng = np.random.default_rng(7)
    circuit = sycamore_circuit(16, 8, rng)
    raw, _ = circuit.into_amplitude_network("0" * 16)
    tn = simplify_network(raw)
    print(f"network: {len(raw)} tensors -> {len(tn)} cores")

    partitioning = find_partitioning(tn, n_devices)
    ptn, ppath, parallel_cost, serial_cost = compute_solution(
        tn, partitioning, rng=random.Random(7)
    )
    print(
        f"partitioned over {n_devices} devices: critical path "
        f"{parallel_cost:.3e} flops (serial {serial_cost:.3e})"
    )

    # slice until each per-slice program is tiny (toy target); on real
    # hardware, omit target_size and the device HBM budget decides
    result, slicing = distributed_partitioned_sliced_contraction(
        ptn, ppath, n_devices=n_devices, target_size=2**10
    )
    amp = complex(np.asarray(result.data.into_data()).reshape(-1)[0])
    print(
        f"composed run: {slicing.num_slices} slices x {n_devices} devices "
        f"-> amplitude {amp:.6g}"
    )

    flat = Greedy(OptMethod.GREEDY).find_path(tn)
    oracle = contract_tensor_network(tn, flat.replace_path(), backend="numpy")
    want = complex(np.asarray(oracle.data.into_data()).reshape(-1)[0])
    assert abs(amp - want) <= 1e-5 * max(1.0, abs(want)), (amp, want)
    print("matches the single-device oracle")


if __name__ == "__main__":
    main()
