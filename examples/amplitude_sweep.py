"""Amplitude sweep: many bitstrings through one compiled program.

Beyond the reference (which re-enters the whole pipeline per amplitude,
``benchmark/src/main.rs``): an amplitude network's structure doesn't
depend on the bitstring, so one contraction path + one jitted XLA
program evaluates a whole batch of amplitudes via ``vmap`` over the bra
values — a single device dispatch, MXU-batched.

Run:  python examples/amplitude_sweep.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from tnc_tpu.builders.random_circuit import random_open_circuit
from tnc_tpu.builders.connectivity import ConnectivityLayout
from tnc_tpu.tensornetwork import amplitude_sweep


def main() -> None:
    rng = np.random.default_rng(7)
    qubits, depth = 16, 10
    circuit = random_open_circuit(
        qubits, depth, 0.4, 0.4, rng, ConnectivityLayout.LINE
    )

    sample = np.random.default_rng(0)
    bitstrings = [
        "".join(sample.choice(["0", "1"]) for _ in range(qubits))
        for _ in range(32)
    ]
    amps = amplitude_sweep(circuit, bitstrings)

    probs = np.abs(amps) ** 2
    print(f"{len(bitstrings)} amplitudes from one compiled program")
    for b, a, p in list(zip(bitstrings, amps, probs))[:5]:
        print(f"  <{b}|C|0...0> = {a:.3e}  |.|^2 = {p:.3e}")
    print(f"  sum of sampled probabilities: {probs.sum():.3e}")


if __name__ == "__main__":
    main()
