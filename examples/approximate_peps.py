"""Approximate contraction: boundary-MPS over a PEPS sandwich.

The reference lists approximate contraction as future work; here a
``chi`` sweep shows the accuracy-for-cost dial against the exact
contraction of a 4×4 PEPS ⟨ψ|O|ψ⟩ sandwich, then the serving tier's
chi-ladder answers the same question with a per-answer error estimate
(docs/approximate.md) and a tolerant amplitude request is served with
an error bar through the service front end.

Run:  python examples/approximate_peps.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from tnc_tpu.builders.peps import peps
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.tensornetwork.approximate import (
    attach_random_data,
    boundary_mps_contract,
    collapse_peps_sandwich,
)
from tnc_tpu.tensornetwork.contraction import contract_tensor_network

LENGTH, DEPTH, LAYERS = 4, 4, 1

rng = np.random.default_rng(11)
tn = attach_random_data(peps(LENGTH, DEPTH, 2, 2, LAYERS), rng)

result = Greedy(OptMethod.GREEDY).find_path(tn)
exact = complex(
    np.asarray(
        contract_tensor_network(tn, result.replace_path(), backend="numpy")
        .data.into_data()
    ).reshape(-1)[0]
)
print(f"exact ⟨ψ|O|ψ⟩ = {exact:.6e}")

grid = collapse_peps_sandwich(tn, LENGTH, DEPTH, LAYERS)
print(f"{DEPTH}x{LENGTH} grid; boundary-MPS chi sweep:")
for chi in (1, 2, 4, 8, 64):
    approx = boundary_mps_contract(grid, chi=chi)
    rel = abs(approx - exact) / abs(exact)
    print(f"  chi={chi:>3}: {approx:.6e}   rel err {rel:.2e}")

assert abs(boundary_mps_contract(grid, chi=64) - exact) <= 1e-8 * abs(exact)
print("chi=64 reproduces the exact value; smaller chi trades accuracy for cost")

# -- the serving tier: chi-ladder with a per-answer error estimate --------
from tnc_tpu.approx import ApproxProgram, ChiLadder  # noqa: E402

program = ApproxProgram.from_peps_sandwich(tn, LENGTH, DEPTH, LAYERS)
res = ChiLadder(chi_cap=64).run(program, rtol=1e-6, scale=abs(exact))
true_err = abs(res.value - exact)
print(
    f"chi-ladder: value {res.value:.6e} ± {res.err:.2e} at chi={res.chi_used} "
    f"after {res.sweeps} sweeps (true err {true_err:.2e})"
)
assert res.converged and res.err >= true_err

# -- fidelity-routed serving: rtol= lands on the approx tier --------------
from tnc_tpu.builders.random_circuit import brickwork_circuit  # noqa: E402
from tnc_tpu.serve import ContractionService  # noqa: E402

circuit = brickwork_circuit(8, 5, np.random.default_rng(0))
with ContractionService.from_circuit(circuit, approx=True) as svc:
    ans = svc.amplitude("10100110", rtol=1e-2)
    tiers = svc.stats()["by_tier"]
print(
    f"service rtol=1e-2: |amp| {abs(ans.value):.6f} ± {ans.err:.1e} "
    f"(chi={ans.chi_used}, escalated={ans.escalated}; "
    f"approx tier served {tiers['approx']['counts']['completed']} request)"
)
