"""Approximate contraction: boundary-MPS over a PEPS sandwich.

The reference lists approximate contraction as future work; here a
``chi`` sweep shows the accuracy-for-cost dial against the exact
contraction of a 4×4 PEPS ⟨ψ|O|ψ⟩ sandwich.

Run:  python examples/approximate_peps.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from tnc_tpu.builders.peps import peps
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.tensornetwork.approximate import (
    attach_random_data,
    boundary_mps_contract,
    collapse_peps_sandwich,
)
from tnc_tpu.tensornetwork.contraction import contract_tensor_network

LENGTH, DEPTH, LAYERS = 4, 4, 1

rng = np.random.default_rng(11)
tn = attach_random_data(peps(LENGTH, DEPTH, 2, 2, LAYERS), rng)

result = Greedy(OptMethod.GREEDY).find_path(tn)
exact = complex(
    np.asarray(
        contract_tensor_network(tn, result.replace_path(), backend="numpy")
        .data.into_data()
    ).reshape(-1)[0]
)
print(f"exact ⟨ψ|O|ψ⟩ = {exact:.6e}")

grid = collapse_peps_sandwich(tn, LENGTH, DEPTH, LAYERS)
print(f"{DEPTH}x{LENGTH} grid; boundary-MPS chi sweep:")
for chi in (1, 2, 4, 8, 64):
    approx = boundary_mps_contract(grid, chi=chi)
    rel = abs(approx - exact) / abs(exact)
    print(f"  chi={chi:>3}: {approx:.6e}   rel err {rel:.2e}")

assert abs(boundary_mps_contract(grid, chi=64) - exact) <= 1e-8 * abs(exact)
print("chi=64 reproduces the exact value; smaller chi trades accuracy for cost")
