"""Repartitioning: improve a partitioning with simulated annealing.

Mirror of the reference's ``tnc/examples/repartitioning.rs:86-113``:
start from the hypergraph partitioner's assignment, then let the SA
engine (IntermediatePartitioningModel — the reference's best model,
``book/src/partitioning.md``) shift subtrees between partitions to
reduce the critical-path cost.

Run:  python examples/repartitioning.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import random

import numpy as np

from tnc_tpu.builders.connectivity import ConnectivityLayout
from tnc_tpu.builders.random_circuit import random_circuit
from tnc_tpu.contractionpath.repartitioning import compute_solution
from tnc_tpu.contractionpath.repartitioning.simulated_annealing import (
    IntermediatePartitioningModel,
    balance_partitions,
)
from tnc_tpu.tensornetwork.partitioning import find_partitioning


def main() -> None:
    rng = np.random.default_rng(7)
    tn = random_circuit(16, 8, 0.9, 0.8, rng, ConnectivityLayout.LINE)

    k = 4
    initial = find_partitioning(tn, k)
    _, _, parallel0, serial0 = compute_solution(tn, initial)
    print(f"initial : parallel flops {parallel0:.3g}  (sum {serial0:.3g})")

    model = IntermediatePartitioningModel(tn)
    sa_rng = random.Random(0)
    best, score = balance_partitions(
        model, model.initial_solution(initial), sa_rng, max_time=10.0
    )
    improved = list(best[0])
    _, _, parallel1, serial1 = compute_solution(tn, improved)
    print(f"annealed: parallel flops {parallel1:.3g}  (sum {serial1:.3g})")
    print(f"improvement: {parallel0 / max(parallel1, 1):.2f}x on the critical path")


if __name__ == "__main__":
    main()
