"""Distributed contraction: partition a Sycamore network over devices.

Mirror of the reference's ``tnc/examples/distributed_contraction.rs``,
with the MPI pipeline replaced by the JAX single-controller model: the
partitioner assigns one sub-network per device, every device contracts
its partition concurrently, and the toplevel path drives the
device-to-device fan-in reduce (ICI on a TPU slice).

Run on any machine (uses however many devices JAX exposes; set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
for an 8-device virtual CPU mesh):

  python examples/distributed_contraction.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from tnc_tpu import CompositeTensor
from tnc_tpu.builders.sycamore_circuit import sycamore_circuit
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.parallel import distributed_partitioned_contraction
from tnc_tpu.tensornetwork.contraction import contract_tensor_network
from tnc_tpu.tensornetwork.partitioning import (
    find_partitioning,
    partition_tensor_network,
)


def main() -> None:
    import jax

    devices = jax.devices()
    print(f"{len(devices)} {devices[0].platform} device(s)")

    rng = np.random.default_rng(42)
    circuit = sycamore_circuit(12, 8, rng)
    tn, _ = circuit.into_amplitude_network("0" * 12)

    k = min(len(devices), 4)
    partitioning = find_partitioning(tn, k)
    grouped = partition_tensor_network(CompositeTensor(list(tn.tensors)), partitioning)
    print(f"partitioned into {len(grouped)} blocks")

    # nested paths per partition + toplevel communication schedule
    path = Greedy(OptMethod.GREEDY).find_path(grouped).replace_path()

    out = distributed_partitioned_contraction(grouped, path)
    amplitude = complex(np.asarray(out.data.into_data()).reshape(-1)[0])
    print(f"amplitude <0...0|C|0...0> = {amplitude}")

    # single-device oracle
    flat = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    want = complex(contract_tensor_network(tn, flat).data.into_data())
    print(f"oracle                    = {want}")
    assert abs(amplitude - want) < 1e-4


if __name__ == "__main__":
    main()
