"""Choosing a parallelism strategy: partitioning vs tree-cut vs slices.

Round-5 addition, beyond the reference (whose only distributed shape is
MPI partitioning, ``tnc/src/mpi/communication.rs``): the same network
can be parallelized three ways, and which one wins is an empirical
question the planner should answer per instance — not doctrine.

1. SA-rebalanced hypergraph partitioning (the reference's shape);
2. tree-cut partitioning: contiguous frontier of one good serial tree,
   local paths preserved (``tnc_tpu.contractionpath.treecut``);
3. slice-parallel SPMD: every device runs a share of the slices of the
   SAME serial plan, one psum combines
   (``tnc_tpu.parallel.sliced_parallel``).

Run (8-device virtual CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  TNC_TPU_PLATFORM=cpu python examples/strategy_selection.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import random as pyrandom

import numpy as np

from tnc_tpu.builders.connectivity import ConnectivityLayout
from tnc_tpu.builders.random_circuit import random_circuit
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.contractionpath.repartitioning import (
    compute_solution,
    compute_solution_with_paths,
)
from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.slicing import (
    find_parallel_slicing,
    sliced_flops,
)
from tnc_tpu.contractionpath.treecut import plan_treecut
from tnc_tpu.parallel import distributed_sliced_contraction, make_mesh
from tnc_tpu.tensornetwork.contraction import contract_tensor_network
from tnc_tpu.tensornetwork.simplify import simplify_network


def main() -> None:
    import jax

    k = min(8, len(jax.devices()))
    rng = np.random.default_rng(7)
    tn = simplify_network(
        random_circuit(
            18, 12, 0.5, 0.5, rng, ConnectivityLayout.SYCAMORE,
            bitstring="0" * 18,
        )
    )
    serial = Greedy(OptMethod.GREEDY).find_path(tn)
    print(f"network: {len(tn.tensors)} cores, serial plan {serial.flops:.3g} flops")

    # 1. hypergraph partitioning (min-cut + greedy local paths)
    from tnc_tpu.tensornetwork.partitioning import find_partitioning

    assignment = find_partitioning(tn, k)
    _, _, par1, ser1 = compute_solution(
        tn, assignment, rng=pyrandom.Random(0)
    )
    print(f"partitioned : critical {par1:.3g}  (vs serial plan "
          f"{serial.flops / par1:.2f}x)")

    # 2. tree-cut: frontier of the serial tree, local paths preserved
    tc = plan_treecut(list(tn.tensors), serial.ssa_path.toplevel, k, steps=2000)
    _, _, par2, ser2 = compute_solution_with_paths(
        tn, tc.assignment, tc.local_paths,
        communication_scheme=CommunicationScheme.WEIGHTED_BRANCH_BOUND,
        rng=pyrandom.Random(0),
    )
    print(f"tree-cut    : critical {par2:.3g}  (vs serial plan "
          f"{serial.flops / par2:.2f}x)")

    # 3. slice-parallel: k-divisible slices of the serial plan
    replace = serial.replace_path()
    psl = find_parallel_slicing(list(tn.tensors), replace.toplevel, k)
    tot = sliced_flops(list(tn.tensors), replace.toplevel, psl)
    print(f"slice-SPMD  : critical {tot / k:.3g}  (overhead "
          f"{tot / serial.flops:.2f}x, vs serial plan "
          f"{serial.flops / (tot / k):.2f}x)")

    # execute the slice-parallel plan on the mesh and check it
    mesh = make_mesh(k)
    out = distributed_sliced_contraction(tn, replace, psl, mesh=mesh)
    got = complex(np.asarray(out.data.into_data()).reshape(-1)[0])
    want = complex(
        contract_tensor_network(tn, replace, backend="numpy").data.into_data()
    )
    err = abs(got - want) / max(1.0, abs(want))
    print(f"mesh run over {k} devices: amplitude {got:.6g} "
          f"(parity {err:.2e})")
    assert err <= 1e-5


if __name__ == "__main__":
    main()
