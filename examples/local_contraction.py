"""Local contraction: QASM2 circuit → statevector on one device.

Mirror of the reference's ``tnc/examples/local_contraction.rs:13-50``:
import a QASM2 circuit, build the statevector network, find a greedy
path, contract, and restore natural qubit order.

Run:  python examples/local_contraction.py
"""

import sys
from pathlib import Path

try:
    import tnc_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.io.qasm import import_qasm
from tnc_tpu.tensornetwork.contraction import contract_tensor_network

QASM = """
OPENQASM 2.0;
include "qelib1.inc";
qreg q[3];
h q[0];
cx q[0], q[1];
cx q[1], q[2];
"""


def main() -> None:
    circuit = import_qasm(QASM)
    tn, permutor = circuit.into_statevector_network()

    result = Greedy(OptMethod.GREEDY).find_path(tn)
    print(f"path found: flops={result.flops:.0f} size={result.size:.0f}")

    # backend="jax" runs the whole path as one XLA program (TPU when
    # available); "numpy" is the CPU oracle.
    final = contract_tensor_network(tn, result.replace_path(), backend="jax")
    final = permutor.apply(final)

    statevector = np.asarray(final.data.into_data()).reshape(-1)
    print("GHZ statevector:")
    for i, amp in enumerate(statevector):
        if abs(amp) > 1e-12:
            print(f"  |{i:03b}⟩  {amp:.6f}")


if __name__ == "__main__":
    main()
